"""Hierarchical cross-slice collectives: ICI inside, DCN between.

TPU-native equivalent of the reference's two-level pattern (reference:
coll/sm intra-node + tuned inter-node selection, SURVEY §2.6
"Hierarchical/topology-aware"; SURVEY §7 step 7: "hierarchical
collectives (intra-slice ICI reduce → inter-slice exchange → ICI
bcast)"). The three phases:

1. **intra-slice reduce** on the slice's communicator — device-resident,
   MXU/VPU combine (the coll/sm analog, but on the fabric);
2. **inter-slice exchange** among slice leaders over DCN — staged
   through the host pool, combined with the native op kernels
   (ring or recursive-doubling schedule over the wire);
3. **intra-slice bcast** of the global result back over ICI.

`SliceHandle` carries one slice's view (its communicator + DCN endpoint
+ peer wiring). In production each controller process holds one handle;
tests hold several in one process (the reference's
multi-rank-over-loopback strategy, SURVEY §4).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..core import config
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger
from ..ops import lookup as op_lookup

logger = get_logger("coll.hier")

_HIER_TAG = 0x48494552  # "HIER"

# Tuned decision knobs for the inter-slice phase (reference lineage:
# coll_tuned_decision_fixed.c:45-87 — allreduce <10KB -> recursive
# doubling, large -> (segmented) ring with 1MiB segments).
_schedule_var = config.register(
    "coll", "hier", "schedule", type=str, default="",
    description="Force the inter-slice schedule (rd|ring|gather); "
                "empty = tuned decision",
)
_small_var = config.register(
    "coll", "hier", "small_msg", type=int, default=10_000,
    description="Bytes below which small-message schedules are chosen "
                "(reference: coll_tuned_decision_fixed.c:53)",
)
_segment_var = config.register(
    "coll", "hier", "segment_bytes", type=int, default=1 << 20,
    description="Segment size for pipelining the intra-slice reduce "
                "against the inter-slice wire (reference: 1MiB ring "
                "segments, coll_tuned_decision_fixed.c:73)",
)


def choose_schedule(n_slices: int, nbytes: int) -> str:
    """The per-(leaders, bytes) decision (coll/tuned's fixed rules,
    restricted to the inter-slice exchange):

    - forced override via coll_hier_schedule;
    - small messages: recursive doubling (pof2 leader counts) or
      gather-at-leader (non-pof2 — one extra hop beats 2(n-1) latency
      terms of a ring at tiny sizes);
    - large messages: ring (bandwidth-optimal, segment-pipelined).
    """
    forced = (_schedule_var.value or "").strip()
    if forced:
        return forced
    pof2 = n_slices & (n_slices - 1) == 0
    if nbytes < _small_var.value:
        return "rd" if pof2 else "gather"
    return "ring"


class HierError(OmpiTpuError):
    errclass = "ERR_OTHER"


@dataclass
class SliceHandle:
    """One slice's participation in a hierarchical collective."""

    comm: object  # intra-slice communicator
    endpoint: object  # DcnEndpoint (leader's listener)
    slice_id: int
    n_slices: int
    peer_ids: dict  # slice_id -> DCN peer id (leader wiring)

    def __post_init__(self):
        # (src_slice, tag) -> payloads that arrived out of order: a
        # fast peer's round-k+1 message can land before a slow peer's
        # round-k one (the reason ob1 has matching queues)
        self._reorder: dict = {}

    def wire_check(self) -> None:
        missing = [
            s for s in range(self.n_slices)
            if s != self.slice_id and s not in self.peer_ids
        ]
        if missing:
            raise HierError(
                f"slice {self.slice_id}: unwired peers {missing}"
            )

    def recv_from(self, src_slice: int, tag: int,
                  timeout: float) -> bytes:
        """Receive the message from `src_slice` with `tag`, buffering
        any other traffic (wire convention: connect cookie is
        slice_id+1, so a passive link's peer id is -(src_slice+1))."""
        key = (src_slice, tag)
        q = self._reorder.get(key)
        if q:
            return q.pop(0)
        deadline = time.monotonic() + timeout
        passive_peer = -(src_slice + 1)
        while True:
            got = self.endpoint.poll_recv()
            if got is None:
                # fail fast when the source slice's links are all gone
                # instead of burning the whole timeout (peer_links is
                # -1 while the handshake is still in flight — only a
                # known-then-died peer trips this)
                if self.endpoint.peer_links(passive_peer) == 0:
                    self.endpoint.check_peer(
                        passive_peer, what=f"slice {src_slice}"
                    )
                if time.monotonic() >= deadline:
                    raise HierError(
                        f"slice {self.slice_id}: timeout waiting for "
                        f"{key}"
                    )
                time.sleep(0.0002)
                continue
            peer, got_tag, raw = got
            src = -peer - 1 if peer < 0 else None
            if src is None:
                raise HierError(
                    f"slice {self.slice_id}: message on active link "
                    f"(peer {peer}); hier traffic must arrive passively"
                )
            if (src, got_tag) == key:
                return raw
            self._reorder.setdefault((src, got_tag), []).append(raw)


def _exchange_ring(h: SliceHandle, block: np.ndarray, op,
                   timeout: float, tag_base: int = _HIER_TAG
                   ) -> np.ndarray:
    """Inter-slice reduce via a ring over DCN: n-1 rounds, each slice
    forwards the partial to the next slice (reference:
    allreduce_intra_ring's structure, over the wire)."""
    # Circulate each slice's ORIGINAL block around the ring while
    # accumulating separately — forwarding the accumulator instead
    # double-counts contributions for n >= 3.
    acc = block.copy()
    cur = block
    right = (h.slice_id + 1) % h.n_slices
    left = (h.slice_id - 1) % h.n_slices
    for rnd in range(h.n_slices - 1):
        h.endpoint.send_bytes(
            h.peer_ids[right], tag_base + rnd, cur.tobytes()
        )
        raw = h.recv_from(left, tag_base + rnd, timeout)
        cur = np.frombuffer(raw, block.dtype).reshape(block.shape)
        acc = op.np_reduce(acc, cur)
    return acc


def _exchange_rd(h: SliceHandle, block: np.ndarray, op,
                 timeout: float, tag_base: int = _HIER_TAG
                 ) -> np.ndarray:
    """Recursive doubling over DCN (reference:
    allreduce_intra_recursivedoubling) — log2(n) rounds for
    power-of-two slice counts."""
    acc = block.copy()
    dist = 1
    rnd = 0
    while dist < h.n_slices:
        partner = h.slice_id ^ dist
        h.endpoint.send_bytes(
            h.peer_ids[partner], tag_base + rnd, acc.tobytes()
        )
        raw = h.recv_from(partner, tag_base + rnd, timeout)
        incoming = np.frombuffer(raw, block.dtype).reshape(block.shape)
        acc = op.np_reduce(acc, incoming)
        dist <<= 1
        rnd += 1
    return acc


def _exchange_gather(h: SliceHandle, block: np.ndarray, op,
                     timeout: float, tag_base: int = _HIER_TAG
                     ) -> np.ndarray:
    """Gather-at-leader: every slice sends its partial to slice 0,
    which reduces and broadcasts the result back — 2 latency terms
    total, the small-message winner for non-pof2 leader counts
    (reference analog: reduce+bcast 'nonoverlapping',
    coll_base_allreduce.c:53)."""
    if h.slice_id == 0:
        acc = block.copy()
        for src in range(1, h.n_slices):
            raw = h.recv_from(src, tag_base, timeout)
            acc = op.np_reduce(
                acc, np.frombuffer(raw, block.dtype).reshape(block.shape)
            )
        for dst in range(1, h.n_slices):
            h.endpoint.send_bytes(
                h.peer_ids[dst], tag_base + 1, acc.tobytes()
            )
        return acc
    h.endpoint.send_bytes(h.peer_ids[0], tag_base, block.tobytes())
    raw = h.recv_from(0, tag_base + 1, timeout)
    return np.frombuffer(raw, block.dtype).reshape(block.shape)


def allreduce(h: SliceHandle, x, op="sum", *, timeout: float = 30.0,
              schedule: Optional[str] = None,
              segment_bytes: Optional[int] = None):
    """Hierarchical allreduce of a rank-major intra-slice buffer. In
    production each controller process drives its own handle; tests
    drive several handles on threads (endpoints are thread-safe).

    Large payloads pipeline: the buffer splits into segments, every
    segment's intra-slice reduce is enqueued on the devices up front
    (JAX async dispatch), and the wire exchanges segment k while the
    devices still compute segments k+1... — the overlap of phase 1
    with phase 2 (reference analog: segmented ring, 1MiB segments,
    coll_tuned_decision_fixed.c:73-81)."""
    seg = segment_bytes if segment_bytes is not None \
        else int(_segment_var.value)
    arr = x if hasattr(x, "nbytes") else None
    per_rank_bytes = (arr.nbytes // h.comm.size) if arr is not None else 0
    if h.n_slices > 1 and seg > 0 and per_rank_bytes > seg:
        return _allreduce_pipelined(h, x, op, timeout=timeout,
                                    schedule=schedule, seg_bytes=seg)
    partial = phase1_local_reduce(h, x, op)
    global_block = phase2_exchange(
        h, partial, op, timeout=timeout, schedule=schedule
    )
    return phase3_local_bcast(h, global_block)


def _allreduce_pipelined(h: SliceHandle, x, op, *, timeout: float,
                         schedule: Optional[str], seg_bytes: int):
    import jax
    import jax.numpy as jnp

    opo = op_lookup(op)
    n = h.comm.size
    flat = x.reshape(n, -1)
    elems = int(flat.shape[1])
    itemsize = jnp.dtype(flat.dtype).itemsize
    seg_elems = max(1, seg_bytes // itemsize)
    bounds = list(range(0, elems, seg_elems)) + [elems]
    # Phase 1 for EVERY segment is enqueued before any wire work: the
    # device runs ahead of the exchange loop below.
    reduced = [
        h.comm.reduce(flat[:, lo:hi],
                      op=opo.name if opo.predefined else opo, root=0)
        for lo, hi in zip(bounds, bounds[1:])
    ]
    SPC.record("hier_pipelined_allreduces")
    rounds_span = h.n_slices + 2  # tag namespace per segment
    out_segs = []
    for s, dev_red in enumerate(reduced):
        partial = np.asarray(jax.device_get(dev_red))
        out_segs.append(phase2_exchange(
            h, partial, op, timeout=timeout, schedule=schedule,
            tag_base=_HIER_TAG + s * rounds_span,
        ))
        SPC.record("hier_segments")
    full = np.concatenate([seg.reshape(-1) for seg in out_segs])
    return phase3_local_bcast(h, full.reshape(x.shape[1:]))


def phase1_local_reduce(h: SliceHandle, x, op="sum") -> np.ndarray:
    op = op_lookup(op)
    red = h.comm.reduce(x, op=op.name if op.predefined else op, root=0)
    import jax

    SPC.record("hier_local_reduce")
    return np.asarray(jax.device_get(red))


def phase2_exchange(h: SliceHandle, partial: np.ndarray, op="sum", *,
                    timeout: float = 30.0,
                    schedule: Optional[str] = None,
                    tag_base: int = _HIER_TAG) -> np.ndarray:
    """Inter-slice combine. Schedule per (leaders, bytes) from the
    tuned decision (`choose_schedule`), overridable via `schedule`
    ('rd'|'ring'|'gather') or the coll_hier_schedule config var."""
    op = op_lookup(op)
    if h.n_slices == 1:
        return partial
    h.wire_check()
    if schedule is None:
        schedule = choose_schedule(h.n_slices, int(partial.nbytes))
    if schedule == "rd":
        if h.n_slices & (h.n_slices - 1):
            raise HierError(
                "recursive doubling needs a power-of-two slice count"
            )
        out = _exchange_rd(h, partial, op, timeout, tag_base)
    elif schedule == "ring":
        out = _exchange_ring(h, partial, op, timeout, tag_base)
    elif schedule == "gather":
        out = _exchange_gather(h, partial, op, timeout, tag_base)
    else:
        raise HierError(f"unknown schedule {schedule!r}")
    SPC.record("hier_dcn_exchanges")
    SPC.record(f"hier_sched_{schedule}")
    return out


def phase3_local_bcast(h: SliceHandle, global_block: np.ndarray):
    buf = h.comm.put_rank_major(
        np.ascontiguousarray(
            np.broadcast_to(
                global_block, (h.comm.size,) + global_block.shape
            )
        )
    )
    SPC.record("hier_local_bcast")
    return h.comm.bcast(buf, root=0)


def wire_slices(handles: list[SliceHandle], *, nlinks: int = 1) -> None:
    """Test/loopback wiring: connect every handle's endpoint to every
    other (production uses modex.exchange_dcn_addresses + connect)."""
    for a in handles:
        for b in handles:
            if a.slice_id == b.slice_id:
                continue
            if b.slice_id not in a.peer_ids:
                a.peer_ids[b.slice_id] = a.endpoint.connect(
                    b.endpoint.address[0], b.endpoint.address[1],
                    cookie=a.slice_id + 1, nlinks=nlinks,
                )

"""Per-tier circuit breaker for collective algorithm selection.

A pallas/quant kernel fault or a transport failure inside one
algorithm tier used to abort the collective; production traffic wants
the T3/EQuARX-style tiers to *degrade* instead — fall to the next
cheaper tier, keep the training step, and re-probe the fast tier once
it has had time to recover. Classic circuit breaker, keyed by
(operation, algorithm):

    CLOSED     tier healthy, used normally
    OPEN       tier tripped (`coll_breaker_threshold` consecutive
               failures); selection routes around it until
               `coll_breaker_cooldown_ms` elapses
    HALF_OPEN  cooldown elapsed; the next call may probe the tier —
               success closes it, failure re-opens (and restarts the
               cooldown)

Integration (coll/tuned.py):

- decision time — ``route(op, algo)`` walks the degradation chain
  (quant_pallas → quant_ring → ring → gather_reduce) past every OPEN
  tier; this also covers the traced path (parallel/bucketer) where
  runtime catching is impossible,
- dispatch time — ``TunedColl.allreduce`` catches a tier failure,
  calls ``record_failure`` and retries the next tier, recording the
  ``coll_tier_fallbacks`` SPC.

State is process-local and advisory: every rank degrades the same way
only if every rank observes the fault — rank-divergent tier choices
produce rank-divergent *results* only for quant tiers, which is why
the fallback target of every quant tier is the plain-precision chain
(bit-identical across ranks regardless of breaker state).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..core import clock
from ..core import config
from ..core.counters import SPC
from ..core.logging import get_logger

logger = get_logger("coll.breaker")

_enable = config.register(
    "coll", "breaker", "enable", type=bool, default=True,
    description="Degrade collective tiers on kernel/transport fault "
    "instead of failing the call",
)
_threshold = config.register(
    "coll", "breaker", "threshold", type=int, default=1,
    description="Consecutive tier failures before the breaker opens",
)
_cooldown = config.register(
    "coll", "breaker", "cooldown_ms", type=int, default=30000,
    description="How long an OPEN tier stays routed-around before a "
    "half-open re-probe",
)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

# Degradation chain: derived from the schedule lattice (coll/sched/
# lattice.py) — the single declarative algorithm -> (tier, fallback)
# map that health/ledger's tier_of_algo also reads. The breaker's
# routing is a deny-set walk over that lattice where the deny set is
# the OPEN/denied tiers of the moment. sched/lattice is pure data
# (stdlib only), so this import cannot cycle.
from .sched import lattice as _lattice  # noqa: E402

NEXT_TIER = _lattice.fallback_map()
TERMINAL = _lattice.TERMINAL


class _Tier:
    __slots__ = ("state", "failures", "opened_at", "probing")

    def __init__(self) -> None:
        self.state = CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.probing = False


_tiers: dict[tuple[str, str], _Tier] = {}
_mu = threading.Lock()
# Bumped on every recorded failure/success/reset. The tuned fast
# dispatch cache stamps itself with this (plus config.generation());
# any breaker activity invalidates the memoized route.
_generation = 0


def enabled() -> bool:
    return _enable.value


def generation() -> int:
    """Monotonic breaker-activity stamp (cache invalidation)."""
    with _mu:
        return _generation


def quiet() -> bool:
    """True when no tier is in a non-CLOSED state — the precondition
    for memoizing a routed dispatch (an OPEN tier's cooldown expiry is
    a lazy transition that a memoized route would never observe)."""
    if not _tiers:
        return True
    with _mu:
        return all(t.state == CLOSED for t in _tiers.values())


def _get(op: str, algo: str) -> _Tier:
    t = _tiers.get((op, algo))
    if t is None:
        t = _tiers[(op, algo)] = _Tier()
    return t


def state(op: str, algo: str) -> str:
    with _mu:
        return _tiers.get((op, algo), _Tier()).state


def is_open(op: str, algo: str) -> bool:
    """True while the tier should be routed around. An OPEN tier whose
    cooldown has elapsed transitions to HALF_OPEN and lets ONE caller
    through as the probe (subsequent callers keep routing around until
    the probe reports)."""
    if not _enable.value or not _tiers:
        return False
    with _mu:
        t = _tiers.get((op, algo))
        if t is None or t.state == CLOSED:
            return False
        if t.state == OPEN:
            elapsed_ms = (clock.monotonic() - t.opened_at) * 1e3
            if elapsed_ms < _cooldown.value:
                return True
            t.state = HALF_OPEN
            t.probing = False
        # HALF_OPEN: admit exactly one probe
        if not t.probing:
            t.probing = True
            SPC.record("coll_breaker_reprobes")
            from ..trace import span as tspan

            tspan.instant("breaker.reprobe", cat="coll", op=op,
                          algo=algo)
            logger.info("breaker %s/%s: half-open re-probe", op, algo)
            return False
        return True


def record_failure(op: str, algo: str) -> None:
    global _generation
    with _mu:
        _generation += 1
        t = _get(op, algo)
        t.failures += 1
        if t.state == HALF_OPEN or t.failures >= _threshold.value:
            if t.state != OPEN:
                SPC.record("coll_breaker_trips")
                from ..trace import span as tspan

                tspan.instant("breaker.trip", cat="coll", op=op,
                              algo=algo, failures=t.failures)
                logger.warning(
                    "breaker %s/%s: OPEN after %d failure(s); "
                    "degrading to %r for %d ms", op, algo, t.failures,
                    NEXT_TIER.get(algo, TERMINAL), _cooldown.value,
                )
            t.state = OPEN
            t.opened_at = clock.monotonic()
            t.probing = False


def record_success(op: str, algo: str) -> None:
    global _generation
    if not _tiers:  # hot path: nothing ever tripped, skip the lock
        return
    with _mu:
        t = _tiers.get((op, algo))
        if t is None:
            return
        if t.state != CLOSED or t.failures:
            _generation += 1
        if t.state != CLOSED:
            logger.info("breaker %s/%s: probe succeeded, CLOSED", op,
                        algo)
        t.state = CLOSED
        t.failures = 0
        t.probing = False


def next_tier(algo: str) -> Optional[str]:
    """The next-cheaper tier, or None at the end of the chain."""
    if algo == TERMINAL:
        return None
    return NEXT_TIER.get(algo, TERMINAL)


def on_tier_restored(tier: str) -> None:
    """health-ledger restore hook: the transport tier is HEALTHY
    again, so close every (op, algo) breaker riding it — the next
    dispatch goes straight back to the fast tier instead of waiting
    out each breaker's own cooldown."""
    global _generation
    if not _tiers:
        return
    from ..health.ledger import tier_of_algo

    with _mu:
        closed = []
        for (op, algo), t in _tiers.items():
            if t.state != CLOSED and tier_of_algo(algo) == tier:
                t.state = CLOSED
                t.failures = 0
                t.probing = False
                closed.append((op, algo))
        if closed:
            _generation += 1
    for op, algo in closed:
        logger.info("breaker %s/%s: closed by tier %r restore", op,
                    algo, tier)


def _health_denied(algo: str, scope: Optional[str]) -> bool:
    """True when the algorithm's transport tier is QUARANTINED in the
    health ledger (comm scope or global). Checked lock-free first so
    the fully-healthy hot path costs two attribute loads."""
    from ..health import ledger as _hl

    if _hl.LEDGER.quiet():
        return False
    return _hl.LEDGER.is_denied(_hl.tier_of_algo(algo), scope)


def route(op: str, algo: str, *, deny: tuple = (),
          scope: Optional[str] = None) -> str:
    """Walk the degradation chain past OPEN/denied/quarantined tiers.
    ``scope`` is the calling communicator's health scope (its cid);
    the health ledger's QUARANTINED verdict denies the whole transport
    tier, on top of the per-(op, algo) breaker state. Records the
    ``coll_tier_fallbacks`` SPC per step so monitoring sees routed
    degradation, not just dispatch-time retries."""
    if not _enable.value:
        return algo
    from ..health import ledger as _hl

    if not _tiers and not deny and _hl.LEDGER.quiet():
        return algo
    seen = []
    while algo in deny or is_open(op, algo) \
            or _health_denied(algo, scope):
        seen.append(algo)
        nxt = next_tier(algo)
        if nxt is None or nxt in seen:
            break
        SPC.record("coll_tier_fallbacks")
        algo = nxt
    if seen:
        from ..trace import span as tspan

        tspan.instant("breaker.fallback", cat="coll", op=op,
                      routed=seen, algo=algo)
        logger.info("breaker: %s routed %s -> %s", op,
                    " -> ".join(seen), algo)
    return algo


def reset() -> None:
    """Forget all tier state (tests / re-init)."""
    global _generation
    with _mu:
        _generation += 1
        _tiers.clear()

"""The schedule lattice: one declarative map from algorithm to
(transport tier, next-cheaper fallback).

Before this module the degradation knowledge lived twice — breaker.py
carried a hand-wired NEXT_TIER dict and health/ledger.py a parallel
_ALGO_TIER map — and every new tier had to be threaded through both.
Now the lattice is the single source of truth: ``breaker.NEXT_TIER``
and ``health.tier_of_algo`` derive from it, and routing around broken
or quarantined tiers is a *deny-set walk over this lattice*
(``route``): start at the chosen algorithm, follow fallback edges past
every denied node, land on the first allowed one. Terminal is
``gather_reduce`` — the ordered pure-XLA + host tier every input
shape/pytree accepts, riding the never-quarantined "host" plane.

Pure data + walks: this module imports nothing from coll/health so it
is safe to import from either side of that boundary.
"""

from __future__ import annotations

from typing import Iterable, Optional

TERMINAL = "gather_reduce"

#: algorithm -> (transport tier, next-cheaper fallback). Tier names are
#: health/ledger's TIERS lattice; a fallback of None ends the chain.
#: Quant tiers fall back to the plain-precision chain (bit-identical
#: across ranks regardless of breaker state); sched_* interpreted
#: schedules fall back within the lattice before leaving it. The
#: sched_pallas_* compiled kernels sit on the distinct "device_pallas"
#: tier and degrade to their interpreted/hand-written equivalent, so a
#: Mosaic-kernel fault quarantines the compiled tier without touching
#: the plain device plane.
LATTICE: dict[str, tuple[str, Optional[str]]] = {
    "sched_pallas_ring": ("device_pallas", "sched_ring"),
    "sched_pallas_ring_seg": ("device_pallas", "sched_ring_seg"),
    "sched_pallas_rs": ("device_pallas", "ring"),
    "quant_pallas": ("device", "quant_ring"),
    "quant_ring": ("device", "ring"),
    "sched_quant": ("device", "sched_ring"),
    "pallas_ring": ("device", "ring"),
    "pallas_bidir": ("device", "ring"),
    "pallas_rd": ("device", "ring"),
    "pallas_ring_chunked": ("device", "ring"),
    "pallas_rsag": ("device", "ring"),
    "sched_hier": ("device", "sched_ring"),
    "sched_rd": ("device", "sched_ring"),
    "sched_ring_seg": ("device", "sched_ring"),
    "sched_ring": ("device", "ring"),
    "ring_segmented": ("device", "ring"),
    "recursive_doubling": ("device", "ring"),
    "ring": ("device", TERMINAL),
    "native": ("device", TERMINAL),
    TERMINAL: ("host", None),
}

#: Default placement for algorithms not named above (rabenseifner,
#: nonoverlapping, bcast trees, ...): they launch XLA programs over the
#: fabric and degrade straight to the terminal.
_DEFAULT = ("device", TERMINAL)


def tier_of(algo: str) -> str:
    """The transport tier an algorithm executes on."""
    return LATTICE.get(algo, _DEFAULT)[0]


def fallback(algo: str) -> Optional[str]:
    """The next-cheaper algorithm, or None at the end of the chain."""
    if algo == TERMINAL:
        return None
    return LATTICE.get(algo, _DEFAULT)[1]


def fallback_map() -> dict[str, str]:
    """The lattice's fallback edges as a plain dict (breaker.NEXT_TIER
    compatibility view)."""
    return {a: nxt for a, (_t, nxt) in LATTICE.items() if nxt is not None}


def chain(algo: str) -> list[str]:
    """The full degradation chain starting at ``algo`` (inclusive)."""
    out = [algo]
    seen = {algo}
    cur = algo
    while True:
        nxt = fallback(cur)
        if nxt is None or nxt in seen:
            return out
        out.append(nxt)
        seen.add(nxt)
        cur = nxt


def route(algo: str, denied: Iterable[str] = ()) -> str:
    """Deny-set walk: the first algorithm on ``algo``'s chain whose
    name is not denied. The terminal is returned even when denied —
    there must always be a routable tier."""
    denied = set(denied)
    last = algo
    for cand in chain(algo):
        last = cand
        if cand not in denied:
            return cand
    return last


__all__ = [
    "LATTICE", "TERMINAL", "chain", "fallback", "fallback_map", "route",
    "tier_of",
]

"""Schedule autotuner: sweep candidates, persist winners.

Per (op, size-bucket, dtype, nranks, topology-fingerprint) key the
tuner scores every candidate schedule and records the winner in the
on-disk cache (sched/cache.py). Two scoring modes
(``coll_sched_autotune_mode``):

``model``
    Deterministic alpha-beta cost model: cost = alpha·steps +
    beta·wire-bytes with per-algorithm step/wire counts and a
    seed-keyed deterministic tie-break. No devices needed — this is
    the offline ``tools/sched warm`` path, and same-seed runs produce
    byte-identical cache digests on every controller (the acceptance
    contract; wall-clock never enters the score).

``measure``
    Wall-clock sweep on a live communicator (tools/tune lineage):
    compile each candidate through coll/framework's compile_plan and
    take the best of ``iters`` timed runs. Winners are
    machine-specific; the digest still excludes the timings.

Health integration: candidates whose transport tier is QUARANTINED in
the health ledger are never timed (or modeled) — a tuner probing a
wedged device tunnel would hang exactly like the traffic it is trying
to route around. The skip is recorded per sweep in the result and on
the ``sched_tune_skipped_quarantined`` SPC.
"""

from __future__ import annotations

import time
import zlib
from functools import partial
from math import ceil, log2
from typing import Optional, Sequence

from ...core import config
from ...core.counters import SPC
from ...core.logging import get_logger
from . import cache as _cache
from . import lattice

logger = get_logger("coll.sched")

_V = partial(config.register, "coll", "sched")
_mode_var = _V(
    "autotune_mode", type=str, default="model",
    description="'model' = deterministic alpha-beta cost model "
                "(reproducible digests, no devices); 'measure' = "
                "wall-clock sweep on a live communicator",
)
_seed_var = _V(
    "autotune_seed", type=int, default=0,
    description="Deterministic tie-break seed for model-mode scoring "
                "(same seed => byte-identical cache digest on every "
                "controller)",
)
_iters_var = _V(
    "autotune_iters", type=int, default=3,
    description="Timed repetitions per candidate in measure mode "
                "(best-of)",
)

#: 4 B .. 1 GiB bytes-per-rank sweep points (one per size decade the
#: bench row reports; tune() buckets them with cache.size_bucket).
DEFAULT_SIZES = (4, 64, 1 << 10, 16 << 10, 256 << 10, 4 << 20,
                 64 << 20, 1 << 30)

#: Candidate allreduce schedules. Quant tiers join only when the user
#: opted into the lossy wire (coll_quant_enable), mirroring the prior's
#: consent gate; pallas tiers join only in measure mode on request
#: (importing them pulls in Mosaic).
_EXACT_CANDIDATES = (
    "native", "recursive_doubling", "ring", "ring_segmented",
    "rabenseifner", "sched_ring", "sched_rd", "sched_ring_seg",
    "sched_hier", "gather_reduce",
)
_QUANT_CANDIDATES = ("quant_ring", "sched_quant")


def candidates(opname: str, nranks: int, dtype=None, op=None, *,
               scope: Optional[str] = None,
               include_pallas: bool = False
               ) -> tuple[list[str], list[str]]:
    """(allowed, skipped_quarantined) candidate algorithm names for the
    sweep. Quarantined transport tiers are never timed."""
    if opname != "allreduce":
        return [], []
    from ...health import ledger as health
    from .. import quant

    pool = list(_EXACT_CANDIDATES)
    if include_pallas:
        pool += ["pallas_ring", "pallas_bidir", "pallas_rd",
                 "sched_pallas_ring", "sched_pallas_ring_seg"]
    if quant._enable_var.value and quant.supports(op or "sum", dtype):
        pool += list(_QUANT_CANDIDATES)
    pof2 = nranks & (nranks - 1) == 0
    if not pof2:
        # rd-family generators need a power-of-two ring; the guarded
        # wrappers would silently re-time the ring, so drop them.
        pool = [a for a in pool
                if a not in ("rabenseifner", "sched_rd", "pallas_rd")]
    allowed, skipped = [], []
    for algo in pool:
        if health.LEDGER.is_denied(lattice.tier_of(algo), scope):
            skipped.append(algo)
            SPC.record("sched_tune_skipped_quarantined")
        else:
            allowed.append(algo)
    return allowed, skipped


# ---------------------------------------------------------------------------
# model mode: deterministic alpha-beta scoring
# ---------------------------------------------------------------------------

#: (alpha per step, beta per wire byte) by transport tier — relative
#: units; only the ordering of costs matters. device_pallas (the sched
#: compiler's fused kernels) beats plain device on both coefficients:
#: no per-round dispatch (one kernel, alpha down) and the DMA overlaps
#: the combine (effective wire cost down).
_TIER_COEFF = {"device_pallas": (0.8, 0.9e-4),
               "device": (1.0, 1.0e-4), "host": (30.0, 8.0e-4)}


def _steps_and_wire(algo: str, nbytes: int, nranks: int) -> tuple:
    """(rounds, bytes-on-wire-per-rank) for the cost model."""
    n = max(2, nranks)
    logn = max(1, ceil(log2(n)))
    ring_wire = 2.0 * nbytes * (n - 1) / n
    if algo in ("native",):
        # fused fabric schedule: bandwidth-optimal wire, fewer
        # exposed steps than the explicit ring
        return logn, ring_wire * 0.85
    if algo in ("recursive_doubling", "sched_rd"):
        return logn, float(nbytes) * logn
    if algo in ("ring", "sched_ring", "pallas_ring", "pallas_bidir",
                "sched_pallas_ring"):
        return 2 * (n - 1), ring_wire
    if algo in ("ring_segmented", "sched_ring_seg",
                "sched_pallas_ring_seg"):
        # segmentation overlaps combine with DMA on large payloads and
        # only adds round overhead on small ones
        factor = 0.92 if nbytes > (1 << 20) else 1.1
        return 2 * (n - 1) + 2, ring_wire * factor
    if algo in ("rabenseifner", "pallas_rsag"):
        return 2 * logn, ring_wire
    if algo in ("quant_ring", "sched_quant", "quant_pallas"):
        from .. import quant

        ratio = max(1.0, quant.compression_ratio())
        # codec cost: one dequant-accumulate-requant pass per hop
        return 2 * (n - 1), ring_wire / ratio + nbytes * 2.0e-1 * 1e-3
    if algo == "sched_hier":
        return n + 2, float(nbytes) * (logn + 1)
    if algo == "gather_reduce":
        return logn, float(nbytes) * n
    return 2 * (n - 1), ring_wire  # unknown: ring-like


def model_cost(algo: str, nbytes: int, nranks: int, seed: int) -> float:
    """Deterministic relative cost; the seed perturbs only the
    tie-break epsilon (crc32 — stable across processes, unlike
    hash())."""
    steps, wire = _steps_and_wire(algo, nbytes, nranks)
    alpha, beta = _TIER_COEFF.get(lattice.tier_of(algo),
                                  _TIER_COEFF["device"])
    jitter = zlib.crc32(f"{seed}:{algo}".encode()) % 997 * 1e-9
    return alpha * steps + beta * wire + jitter


# ---------------------------------------------------------------------------
# measure mode
# ---------------------------------------------------------------------------

def measure_cost(comm, algo: str, nbytes: int, dtype, op,
                 iters: int) -> Optional[float]:
    """Best-of wall seconds for one candidate on a live comm, or None
    when the candidate fails to compile/run for this shape."""
    import jax
    import numpy as np

    from .. import tuned
    from ..framework import compile_plan

    fn = tuned._resolve_algo("allreduce", algo)
    if fn is None:
        return None
    elems = max(1, nbytes // max(1, np.dtype(dtype).itemsize))
    data = np.ones((comm.size, elems), dtype)
    x = comm.put_rank_major(data)
    key = ("sched.tune", algo, op.cache_key, x.shape, str(x.dtype))
    per_rank = lambda b: fn(b, "ranks", op)
    try:
        plan = compile_plan(comm, key, per_rank,
                            check_vma=not tuned.is_pallas_algo(algo))
        jax.block_until_ready(plan(x))  # warmup/compile
        best = float("inf")
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            jax.block_until_ready(plan(x))
            best = min(best, time.perf_counter() - t0)
        return best
    except Exception:  # commlint: allow(broadexcept)
        return None  # candidate invalid for this shape/rank count


# ---------------------------------------------------------------------------
# the sweep
# ---------------------------------------------------------------------------

def tune(nranks: int, *, comm=None, opname: str = "allreduce",
         sizes: Sequence[int] = DEFAULT_SIZES,
         dtypes: Sequence = ("float32",),
         mode: Optional[str] = None, seed: Optional[int] = None,
         topo_fp: Optional[str] = None, save: bool = True,
         include_pallas: bool = False) -> dict:
    """Sweep the candidate space and persist winners.

    Returns {"winners": {key: algo}, "skipped": [...], "path": ...,
    "digest": ..., "tune_ms": ..., "times": {...}} — ``times`` carries
    the per-candidate scores of the last sweep point per dtype (the
    bench row's tuned-vs-static evidence).
    """
    from ...trace import span as tspan
    from ..tuned import _algo_space
    from ...ops import lookup as op_lookup

    mode = mode or _mode_var.value
    seed = _seed_var.value if seed is None else seed
    if mode == "measure" and comm is None:
        raise ValueError("measure mode needs a live communicator")
    if topo_fp is None:
        topo_fp = fingerprint()
    op = op_lookup("sum")
    t0 = time.perf_counter()
    winners: dict[str, str] = {}
    all_times: dict[str, dict[str, float]] = {}
    skipped_all: list[str] = []
    known = _algo_space(opname)
    for dtype in dtypes:
        allowed, skipped = candidates(
            opname, nranks, dtype=dtype, op=op,
            include_pallas=include_pallas,
        )
        skipped_all.extend(a for a in skipped if a not in skipped_all)
        allowed = [a for a in allowed if a in known]
        if not allowed:
            continue
        seen_buckets: set[int] = set()
        for size in sizes:
            bucket = _cache.size_bucket(size)
            if bucket in seen_buckets:
                continue
            seen_buckets.add(bucket)
            times: dict[str, float] = {}
            for algo in allowed:
                if mode == "measure":
                    got = measure_cost(comm, algo, size, dtype, op,
                                       _iters_var.value)
                    if got is not None:
                        times[algo] = got
                else:
                    times[algo] = model_cost(algo, size, nranks, seed)
            if not times:
                continue
            best = min(times, key=times.get)
            key = _cache.cache_key(opname, size, nranks, dtype, topo_fp)
            # the latency/bandwidth frontier rides the entry (excluded
            # from the digest) so SLO selection and retunes can re-rank
            # candidates without a fresh sweep
            frontier = [
                {"algo": a,
                 "score": float(sc),
                 "steps": float(_steps_and_wire(a, size, nranks)[0]),
                 "wire": float(_steps_and_wire(a, size, nranks)[1])}
                for a, sc in sorted(times.items(), key=lambda kv: kv[1])
            ]
            _cache.CACHE.put(
                key, best, schedule=_schedule_id(best, nranks),
                source=mode,
                score=times[best] if mode == "model" else None,
                tune_ms=(times[best] * 1e3 if mode == "measure"
                         else None),
                frontier=frontier,
            )
            winners[key] = best
            tspan.instant("sched.tune_winner", cat="sched", key=key,
                          algo=best, mode=mode,
                          candidates=len(times))
            all_times[f"{dtype}|b{bucket}"] = times
    tune_ms = (time.perf_counter() - t0) * 1e3
    SPC.record("sched_tune_ms", tune_ms)
    out = {
        "winners": winners,
        "skipped": skipped_all,
        "mode": mode,
        "seed": seed,
        "topo_fp": topo_fp,
        "digest": _cache.CACHE.digest(),
        "tune_ms": tune_ms,
        "times": all_times,
        "path": None,
    }
    if save and winners:
        out["path"] = _cache.CACHE.save(
            _cache.default_path(topo_fp, nranks))
    logger.info("sched: tuned %d key(s) in %.1f ms (mode=%s, "
                "skipped=%s)", len(winners), tune_ms, mode,
                skipped_all or "none")
    return out


# ---------------------------------------------------------------------------
# program-level choices (the step as the compilation unit)
# ---------------------------------------------------------------------------

SPC.counter(
    "sched_program_tile_overrides_total",
    "bucket tile geometries taken from the winner cache instead of "
    "the static default when compiling a step program",
)
SPC.counter(
    "sched_program_compiles_total",
    "whole-step comm programs compiled",
)
SPC.counter(
    "sched_window_spans_total",
    "step-boundary window spans armed: a step's merged broadcast tail "
    "dispatched past its own finish into the next step's window "
    "(slipstream)",
)
SPC.counter(
    "sched_ag_elided_total",
    "allgather program nodes elided by shard residency (rs_resident): "
    "the owner shard stays resident on the optimizer path and the next "
    "forward reads it directly",
)
SPC.counter(
    "sched_tail_overlap_ms",
    "milliseconds of merged-broadcast tail execution hidden under the "
    "next step's backward (slipstream window overlap)",
    unit="ms",
)

#: Power-of-two tile-size sweep for the per-bucket geometry model.
PROGRAM_TILE_CANDIDATES = (64 << 10, 128 << 10, 256 << 10, 512 << 10,
                           1 << 20)

#: Per-tile dispatch cost vs per-byte tail-exposure cost (relative
#: units, host transport): every tile pays a stage + Pready burst +
#: drain sweep, while a larger final tile only lengthens the exposed
#: tail — so the model leans toward few large tiles and the overlap
#: granularity stays bucket-level.
_PROG_TILE_A = 6000.0   # per tile
_PROG_TILE_B = 0.02     # per byte of tile exposure

#: RS/AG-vs-allreduce decision: gather-to-root pays one persistent
#: pair per peer and the full bucket through the root's wire; the
#: ZeRO-style split pays n× the pair setup but 1/n of the per-root
#: wire. Crossover ~ _PROG_PAIR_GAMMA·n/_PROG_WIRE_BETA bytes.
_PROG_PAIR_GAMMA = 4000.0  # per persistent pair armed per step
_PROG_WIRE_BETA = 1e-3     # per bucket byte through one root

#: Shard-residency (rs_resident) decision: eliding the allgather saves
#: its full wire share, but the next forward must read the reduced
#: shard from the resident owner (a host-local replication, _ETA per
#: byte) and params consumed early in the forward can't hide that
#: deferred read — _URGENCY decays with the consuming layer's distance
#: (the node's ag_deadline).
_PROG_RESIDENT_ETA = 2e-4      # per byte read from the resident owner
_PROG_RESIDENT_URGENCY = 2000.0  # first-layer penalty, ~1/(1+deadline)


def program_tile_bytes(nbytes: int, nranks: int, seed: int) -> int:
    """Deterministic model winner for one bucket's tile size: argmin
    over the power-of-two sweep of per-tile dispatch cost plus tail
    exposure, seed-jittered for stable tie-breaks (crc32, not hash())."""
    best, best_cost = PROGRAM_TILE_CANDIDATES[0], float("inf")
    for t in PROGRAM_TILE_CANDIDATES:
        tiles = max(1, -(-int(nbytes) // t))
        cost = (_PROG_TILE_A * tiles + _PROG_TILE_B * min(t, nbytes)
                + zlib.crc32(f"{seed}:tile:{t}".encode()) % 997 * 1e-9)
        if cost < best_cost:
            best, best_cost = t, cost
    return best


def ag_elision_wins(nbytes: int, nranks: int, seed: int,
                    ag_deadline: int) -> bool:
    """Shard-residency decision for one RS/AG pair: elide the allgather
    when its wire share beats the resident-owner read plus the
    consume-urgency penalty (seed-jittered tie-break, crc32 never
    hash())."""
    n = max(2, nranks)
    ag_wire = _PROG_WIRE_BETA * nbytes * (n - 1) / n
    read = _PROG_RESIDENT_ETA * nbytes
    urgency = _PROG_RESIDENT_URGENCY / (1.0 + max(0, int(ag_deadline)))
    jitter = (zlib.crc32(f"{seed}:res:{int(ag_deadline)}".encode())
              % 997 * 1e-9)
    return ag_wire > read + urgency + jitter


def program_node_choice(nbytes: int, nranks: int, seed: int, *,
                        ag_deadline: Optional[int] = None,
                        resident: Optional[bool] = None) -> str:
    """'allreduce' (gather-to-root + merged bcast) vs 'rs_ag' (ZeRO-
    style reduce-scatter + allgather pair) for one bucket, by the
    pair-setup/root-wire cost model.

    With an ``ag_deadline`` (the step-N+1 forward layer that first
    consumes this bucket) the pair choice may deepen into
    'rs_resident': the allgather node is elided entirely and the next
    forward reads the reduced shard from the resident owner (ZeRO-2/3).
    ``resident`` pins a cache-learned residency decision (True forces
    the elision, False forbids it, None lets the model decide)."""
    n = max(2, nranks)
    cost_ar = (_PROG_PAIR_GAMMA * (n - 1)
               + _PROG_WIRE_BETA * nbytes * (n - 1)
               + zlib.crc32(f"{seed}:ar".encode()) % 997 * 1e-9)
    cost_rs = (_PROG_PAIR_GAMMA * n * (n - 1)
               + _PROG_WIRE_BETA * nbytes * (n - 1) / n
               + zlib.crc32(f"{seed}:rs".encode()) % 997 * 1e-9)
    base = "allreduce" if cost_ar <= cost_rs else "rs_ag"
    if nranks < 2:
        return base
    if resident is not None:
        return "rs_resident" if resident else base
    if (base == "rs_ag" and ag_deadline is not None
            and ag_elision_wins(nbytes, nranks, seed, ag_deadline)):
        return "rs_resident"
    return base


def program_choices(bucket_nbytes: Sequence[int], nranks: int, *,
                    dtypes: Optional[Sequence] = None,
                    seed: Optional[int] = None,
                    topo_fp: Optional[str] = None,
                    tile_bytes=None,
                    node_choices: Optional[Sequence] = None,
                    ag_deadlines: Optional[Sequence] = None) -> list:
    """Program-level search for one training step: per bucket, the
    tile geometry (caller > winner cache > model, in that precedence),
    the RS/AG-vs-allreduce schedule decision, and the cross-bucket
    interleave rank. Deterministic for a fixed (buckets, nranks, seed,
    cache state) — these choices feed the program digest, so same-seed
    controllers must compute byte-identical answers.

    ``ag_deadlines`` (per bucket, None entries allowed) names the
    step-N+1 forward layer that first consumes each bucket; with a
    deadline known the pair choice may deepen into 'rs_resident' (AG
    node elided, owner shard stays resident). Deadline and residency
    follow the same precedence as tile geometry: caller > winner cache
    (``ag_deadline`` / ``resident`` entry fields, carried through
    bump/rollback like tile_bytes) > model. A caller-pinned 'rs_ag'
    with a deadline still consults the residency model — pin
    'rs_resident' or 'allreduce' to fix the choice outright.

    Returns one dict per bucket: {"choice", "tile_bytes",
    "tile_source", "interleave", "ag_deadline"} where interleave is the
    bucket's arm position (biggest buckets first — their wire time is
    the hardest to hide, so they enter the fabric earliest).
    """
    seed = _seed_var.value if seed is None else seed
    if topo_fp is None:
        topo_fp = fingerprint()
    sizes = [int(b) for b in bucket_nbytes]
    out: list[dict] = []
    for i, nbytes in enumerate(sizes):
        dtype = (dtypes[i] if dtypes is not None else "float32")
        ent = _cache.CACHE.get(_cache.cache_key(
            "allreduce", nbytes, nranks, dtype, topo_fp)) or {}
        if tile_bytes is not None:
            tb = (tile_bytes[i] if isinstance(tile_bytes, (list, tuple))
                  else tile_bytes)
            tb, src = int(tb), "caller"
        elif ent.get("tile_bytes"):
            tb, src = int(ent["tile_bytes"]), "cache"
            SPC.record("sched_program_tile_overrides_total")
        else:
            tb, src = program_tile_bytes(nbytes, nranks, seed), "model"
        dl = ag_deadlines[i] if ag_deadlines is not None else None
        if dl is None and ent.get("ag_deadline") is not None:
            dl = int(ent["ag_deadline"])
        resident = ent.get("resident")
        if resident is not None:
            resident = bool(resident)
        if node_choices is not None and node_choices[i]:
            choice = str(node_choices[i])
            if choice == "rs_ag" and nranks >= 2:
                if resident is True:
                    choice = "rs_resident"
                elif (resident is None and dl is not None
                        and ag_elision_wins(nbytes, nranks, seed, dl)):
                    choice = "rs_resident"
        else:
            choice = program_node_choice(nbytes, nranks, seed,
                                         ag_deadline=dl,
                                         resident=resident)
        out.append({"choice": choice, "tile_bytes": tb,
                    "tile_source": src, "interleave": i,
                    "ag_deadline": None if dl is None else int(dl)})
    # Cross-bucket interleave: arm biggest-first, index as tie-break
    # (stable and seed-independent so the order never fights the
    # digest contract).
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    for pos, i in enumerate(order):
        out[i]["interleave"] = pos
    return out


def tune_step(nranks: int, bucket_nbytes: Sequence[int], *,
              dtype="float32", seed: Optional[int] = None,
              topo_fp: Optional[str] = None, save: bool = False) -> dict:
    """Persist model-mode tile-geometry winners for a step's bucket
    sizes into the winner cache (the program-level analog of tune()):
    later compile_step calls on any same-seed controller pick these
    entries up as 'cache'-sourced overrides. Existing algorithm
    winners on a key are preserved (tile_bytes rides the entry)."""
    from ...trace import span as tspan

    seed = _seed_var.value if seed is None else seed
    if topo_fp is None:
        topo_fp = fingerprint()
    keys = []
    for nbytes in {int(b) for b in bucket_nbytes}:
        key = _cache.cache_key("allreduce", nbytes, nranks, dtype,
                               topo_fp)
        tb = program_tile_bytes(nbytes, nranks, seed)
        ent = _cache.CACHE.get(key)
        if ent is None:
            _cache.CACHE.put(key, "native", source="model",
                             tile_bytes=tb)
        else:
            _cache.CACHE.put(
                key, ent["algorithm"],
                schedule=ent.get("schedule", ""),
                source=ent.get("source", "model"),
                tile_bytes=tb)
        tspan.instant("sched.tune_step_tile", cat="sched", key=key,
                      tile_bytes=tb, seed=seed)
        keys.append(key)
    out = {"keys": sorted(keys), "seed": seed, "topo_fp": topo_fp,
           "digest": _cache.CACHE.digest(), "path": None}
    if save and keys:
        out["path"] = _cache.CACHE.save(
            _cache.default_path(topo_fp, nranks))
    return out


def tune_residency(nranks: int, bucket_nbytes: Sequence[int],
                   ag_deadlines: Sequence[int], *, dtype="float32",
                   seed: Optional[int] = None,
                   topo_fp: Optional[str] = None,
                   save: bool = False) -> dict:
    """Persist learned shard-residency decisions into the winner cache
    (the slipstream analog of tune_step): for each bucket size, the
    forward-consume deadline and the model's elide-the-AG verdict ride
    the cache entry (``ag_deadline`` / ``resident``), so later
    compile_step/compile_window calls on any same-seed controller
    recover the same residency plan even when the caller passes no
    deadlines. Existing algorithm winners and tile geometry on a key
    are preserved."""
    from ...trace import span as tspan

    seed = _seed_var.value if seed is None else seed
    if topo_fp is None:
        topo_fp = fingerprint()
    keys = []
    for nbytes, dl in zip(bucket_nbytes, ag_deadlines):
        nbytes, dl = int(nbytes), int(dl)
        key = _cache.cache_key("allreduce", nbytes, nranks, dtype,
                               topo_fp)
        resident = (program_node_choice(nbytes, nranks, seed,
                                        ag_deadline=dl)
                    == "rs_resident")
        ent = _cache.CACHE.get(key)
        if ent is None:
            _cache.CACHE.put(key, "native", source="model",
                             ag_deadline=dl, resident=resident)
        else:
            _cache.CACHE.put(
                key, ent["algorithm"],
                schedule=ent.get("schedule", ""),
                source=ent.get("source", "model"),
                tile_bytes=ent.get("tile_bytes"),
                ag_deadline=dl, resident=resident)
        tspan.instant("sched.tune_residency", cat="sched", key=key,
                      ag_deadline=dl, resident=resident, seed=seed)
        keys.append(key)
    out = {"keys": sorted(keys), "seed": seed, "topo_fp": topo_fp,
           "digest": _cache.CACHE.digest(), "path": None}
    if save and keys:
        out["path"] = _cache.CACHE.save(
            _cache.default_path(topo_fp, nranks))
    return out


#: sched_* algorithm name -> ir generator name.
SCHED_GENERATOR = {
    "sched_ring": "ring",
    "sched_rd": "recursive_doubling",
    "sched_ring_seg": "segmented_ring",
    "sched_hier": "hierarchical",
    "sched_quant": "quantized_wire",
    # the pallas-compiled names share their base generator's digest:
    # the step program is identical, only the lowering differs (the
    # lowered-callable memo keys on meta["lowering"] separately).
    "sched_pallas_ring": "ring",
    "sched_pallas_ring_seg": "segmented_ring",
}


def _schedule_id(algo: str, nranks: int) -> str:
    """The IR digest backing a sched_* winner ('' for primitive
    tiers) — recorded in the cache entry so a dumped cache names the
    exact step program version it selected."""
    gen = SCHED_GENERATOR.get(algo)
    if gen is None:
        return ""
    from . import ir

    try:
        return ir.generate(gen, nranks).digest()
    except ir.ScheduleError:
        return ""


_fp_cache: Optional[str] = None


def fingerprint() -> str:
    """The current process's topology fingerprint (cached)."""
    global _fp_cache
    if _fp_cache is None:
        from ...topo import hardware_fingerprint

        _fp_cache = hardware_fingerprint()
    return _fp_cache


def reset_fingerprint() -> None:
    global _fp_cache
    _fp_cache = None


__all__ = [
    "DEFAULT_SIZES", "PROGRAM_TILE_CANDIDATES", "ag_elision_wins",
    "candidates", "fingerprint", "model_cost", "measure_cost",
    "program_choices", "program_node_choice", "program_tile_bytes",
    "reset_fingerprint", "tune", "tune_step", "tune_residency",
]

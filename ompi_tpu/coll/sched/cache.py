"""Versioned on-disk schedule cache.

The autotuner's winners persist as JSON keyed by
``op|size-bucket|dtype|nranks|topology-fingerprint`` so a fleet warms
once: the first controller (or an offline ``tools/sched warm`` run)
sweeps and writes the cache; every later process loads it and
dispatches winners with zero first-call tune cost. Size buckets are
log2 of the **bytes-per-rank** payload — the same convention
Rules._matches and decide_* use (DESIGN.md §18), so a rules band and a
cache entry keyed from the same payload always agree on the byte
count.

Determinism contract: ``digest()`` is the sha256 of the canonical JSON
of {version, entries → {algorithm, schedule}} — wall-clock timings and
scores are stored alongside for inspection but EXCLUDED, so a
same-seed autotune run produces a byte-identical digest on every
controller (the same reproducibility contract the health ledger's
transition digest carries). A version-mismatched file is ignored (and
counted), never migrated: stale schedules must lose to a fresh sweep,
not be reinterpreted.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from functools import partial
from typing import Optional

from ...core import config
from ...core.logging import get_logger

logger = get_logger("coll.sched")

#: Bump when the entry format or the key grammar changes.
VERSION = 1

_V = partial(config.register, "coll", "sched")
_enable_var = _V(
    "cache_enable", type=bool, default=True,
    description="Consult the compiled-schedule cache in decide_* "
                "(static priors remain the cold-start fallback)",
)
_dir_var = _V(
    "cache_dir", type=str, default="",
    description="Directory for the persisted schedule cache "
                "(default: $OMPI_TPU_SCHED_CACHE or "
                "~/.cache/ompi_tpu/sched)",
)


def cache_dir() -> str:
    d = _dir_var.value
    if d:
        return d
    env = os.environ.get("OMPI_TPU_SCHED_CACHE", "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "ompi_tpu",
                        "sched")


def size_bucket(nbytes_per_rank: int) -> int:
    """log2 bucket of a bytes-per-rank payload (0 for <=1 byte)."""
    return max(0, int(nbytes_per_rank).bit_length() - 1)


def bucket_bytes(bucket: int) -> int:
    """Representative bytes-per-rank for a bucket (its lower edge)."""
    return 1 << bucket


def cache_key(opname: str, nbytes_per_rank: int, nranks: int,
              dtype=None, topo_fp: str = "") -> str:
    dt = str(dtype) if dtype is not None else "any"
    return (f"{opname}|b{size_bucket(nbytes_per_rank)}|{dt}"
            f"|r{nranks}|{topo_fp or 'none'}")


def default_path(topo_fp: str, nranks: int) -> str:
    return os.path.join(
        cache_dir(),
        f"sched_v{VERSION}_r{nranks}_{(topo_fp or 'none')[:16]}.json",
    )


class ScheduleCache:
    """In-memory view of the persisted winner table."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._entries: dict[str, dict] = {}
        # paths whose load was already attempted (hit or miss), so the
        # dispatch path stats a missing file at most once per config
        # generation.
        self._load_attempted: dict[str, bool] = {}
        self._config_gen = -1
        # bumped on every content change; memoized dispatch plans
        # (tuned._fast_allreduce) stamp it so a warm/tune invalidates
        # them.
        self._generation = 0
        # shared-read accounting by consumer scope ("tenant:<id>" /
        # "global"): the winner table warms ONCE per controller and
        # every daemon tenant reads the same entries — this meters
        # who benefits without ever scoping the entries themselves.
        self._scope_reads: dict[str, int] = {}

    # -- entries -------------------------------------------------------

    def put(self, key: str, algorithm: str, *, schedule: str = "",
            source: str = "autotune", tune_ms: Optional[float] = None,
            score: Optional[float] = None,
            frontier: Optional[list] = None,
            baseline_p50_us: Optional[float] = None,
            tile_bytes: Optional[int] = None,
            ag_deadline: Optional[int] = None,
            resident: Optional[bool] = None) -> None:
        ent = {"algorithm": algorithm, "schedule": schedule,
               "source": source, "version": 1}
        if tile_bytes is not None:
            ent["tile_bytes"] = int(tile_bytes)
        if ag_deadline is not None:
            ent["ag_deadline"] = int(ag_deadline)
        if resident is not None:
            ent["resident"] = bool(resident)
        if tune_ms is not None:
            ent["tune_ms"] = round(float(tune_ms), 3)
        if score is not None:
            ent["score"] = float(score)
        if frontier is not None:
            ent["frontier"] = list(frontier)
        if baseline_p50_us is not None:
            ent["baseline_p50_us"] = float(baseline_p50_us)
        with self._mu:
            self._entries[key] = ent
            self._generation += 1

    def bump(self, key: str, algorithm: str, *, schedule: str = "",
             source: str = "retune", tune_ms: Optional[float] = None,
             score: Optional[float] = None,
             frontier: Optional[list] = None,
             baseline_p50_us: Optional[float] = None,
             tile_bytes: Optional[int] = None,
             ag_deadline: Optional[int] = None,
             resident: Optional[bool] = None) -> int:
        """Install a new winner as a **version-bumped** entry: the
        prior winner survives one level deep under ``"previous"`` so a
        bad retune can be rolled back. Never mutates the old entry in
        place — a memoized dispatch plan stamped with the previous
        cache generation keeps running its old schedule until its memo
        invalidates. Returns the new version number."""
        new = {"algorithm": algorithm, "schedule": schedule,
               "source": source}
        if tile_bytes is not None:
            new["tile_bytes"] = int(tile_bytes)
        if ag_deadline is not None:
            new["ag_deadline"] = int(ag_deadline)
        if resident is not None:
            new["resident"] = bool(resident)
        if tune_ms is not None:
            new["tune_ms"] = round(float(tune_ms), 3)
        if score is not None:
            new["score"] = float(score)
        if frontier is not None:
            new["frontier"] = list(frontier)
        if baseline_p50_us is not None:
            new["baseline_p50_us"] = float(baseline_p50_us)
        with self._mu:
            old = self._entries.get(key)
            if old is None:
                new["version"] = 1
            else:
                # a retune must not silently drop the step-program tile
                # geometry or shard-residency plan tuned onto this key:
                # carry them forward unless the bump supplies fresh ones
                for carry in ("tile_bytes", "ag_deadline", "resident"):
                    if carry in old and carry not in new:
                        new[carry] = old[carry]
                new["version"] = int(old.get("version", 1)) + 1
                new["previous"] = {
                    "algorithm": old.get("algorithm", ""),
                    "schedule": old.get("schedule", ""),
                    "version": int(old.get("version", 1)),
                    "source": old.get("source", ""),
                }
            self._entries[key] = new
            self._generation += 1
            return new["version"]

    def rollback(self, key: str) -> bool:
        """Restore the ``"previous"`` winner a ``bump()`` retained.
        Returns False when there is nothing to roll back to."""
        with self._mu:
            ent = self._entries.get(key)
            prev = (ent or {}).get("previous")
            if not prev:
                return False
            restored = {"algorithm": prev.get("algorithm", ""),
                        "schedule": prev.get("schedule", ""),
                        "source": prev.get("source", "") or "rollback",
                        "version": int(ent.get("version", 1)) + 1}
            # rolling an algorithm winner back must not drop the
            # key-scoped tuning facts riding the entry (tile geometry,
            # shard-residency plan) — they are orthogonal to which
            # winner is installed, and a watchtower
            # bump-then-rollback cycle would otherwise silently erase
            # the residency decisions every same-seed controller
            # recompiles from
            for carry in ("tile_bytes", "ag_deadline", "resident"):
                if carry in ent:
                    restored[carry] = ent[carry]
            self._entries[key] = restored
            self._generation += 1
            return True

    def set_baseline(self, key: str, p50_us: float) -> None:
        """Stamp the live-measured p50 the watchtower drifts against.
        Non-semantic (excluded from the digest) so observation never
        perturbs the byte-identity contract; does not bump the
        generation for the same reason."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is not None:
                ent["baseline_p50_us"] = float(p50_us)

    def get(self, key: str) -> Optional[dict]:
        return self._entries.get(key)

    def note_read(self, *, scope: str) -> None:
        """Meter one shared winner-table consult by a tenant scope
        (daemon dispatch calls this per collective) — billing-plane
        data, non-semantic: never in the digest."""
        with self._mu:
            self._scope_reads[scope] = \
                self._scope_reads.get(scope, 0) + 1

    def scope_reads(self) -> dict[str, int]:
        with self._mu:
            return dict(self._scope_reads)

    def entries(self) -> dict[str, dict]:
        with self._mu:
            return dict(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._load_attempted.clear()
            self._scope_reads.clear()
            self._config_gen = -1
            self._generation += 1

    def generation(self) -> int:
        """Content-change counter (see __init__)."""
        return self._generation

    # -- digest / persistence ------------------------------------------

    def digest(self) -> str:
        """sha256 over the semantic content only (version + winners);
        timings/scores excluded — the byte-identical-across-controllers
        contract."""
        with self._mu:
            canon = {
                "version": VERSION,
                "entries": {
                    k: {"algorithm": e["algorithm"],
                        "schedule": e.get("schedule", ""),
                        "version": int(e.get("version", 1)),
                        # semantic only when tuned: program tile
                        # geometry and shard-residency plans change
                        # what executes, so they join the digest — but
                        # only when present, keeping pre-program and
                        # pre-slipstream caches' digests byte-stable
                        **({"tile_bytes": int(e["tile_bytes"])}
                           if "tile_bytes" in e else {}),
                        **({"ag_deadline": int(e["ag_deadline"])}
                           if "ag_deadline" in e else {}),
                        **({"resident": bool(e["resident"])}
                           if "resident" in e else {})}
                    for k, e in sorted(self._entries.items())
                },
            }
        blob = json.dumps(canon, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def save(self, path: str) -> str:
        doc = {
            "version": VERSION,
            "digest": self.digest(),
            "entries": self.entries(),
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)  # atomic: readers never see a torn file
        logger.info("sched: saved %d schedule(s) to %s", len(self), path)
        return path

    def load(self, path: str) -> int:
        """Merge entries from ``path``; returns the number loaded.
        Version mismatches and unreadable files load nothing."""
        from ...core.counters import SPC

        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return 0
        if doc.get("version") != VERSION:
            SPC.record("sched_cache_version_mismatch")
            logger.warning(
                "sched: cache %s has version %r (want %d); ignored",
                path, doc.get("version"), VERSION,
            )
            return 0
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            return 0
        loaded = 0
        with self._mu:
            for k, e in entries.items():
                if isinstance(e, dict) and e.get("algorithm"):
                    self._entries[k] = e
                    loaded += 1
            if loaded:
                self._generation += 1
        return loaded

    def ensure_loaded(self, topo_fp: str, nranks: int) -> None:
        """Attempt the default-path disk load once per (path, config
        generation) — a config mutation (cache_dir change, test reset)
        re-arms the attempt."""
        gen = config.generation()
        path = default_path(topo_fp, nranks)
        with self._mu:
            if self._config_gen != gen:
                self._load_attempted.clear()
                self._config_gen = gen
            if self._load_attempted.get(path):
                return
            self._load_attempted[path] = True
        n = self.load(path)
        if n:
            logger.info("sched: warmed %d schedule(s) from %s", n, path)

    def active(self) -> bool:
        """True once any entry exists — the gate for counting misses
        (an unconfigured process should not drown monitoring in
        sched_cache_misses)."""
        return bool(self._entries)


CACHE = ScheduleCache()

__all__ = [
    "CACHE", "VERSION", "ScheduleCache", "bucket_bytes", "cache_dir",
    "cache_key", "default_path", "size_bucket",
]

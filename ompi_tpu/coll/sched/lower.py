"""Lowering: compile a Schedule IR program to a fused jitted callable.

Two lowering modes, selected by ``Schedule.meta["lowering"]``:

``interpret``
    A genuine IR executor: the step program is compiled round-by-round
    into a traced jax program — each round becomes one
    ``lax.ppermute`` (the ICI DMA) driven by per-round index tables
    (who sends which chunk where, who reduces/copies what), the
    reduction is the Op's combine on the VPU/MXU. The tables are
    python-side constants, so the whole schedule unrolls into the XLA
    graph exactly like the hand-written spmd algorithms — and XLA
    fuses/overlaps the rounds of independent chunk chains (segmented
    ring) for free.

``primitive``
    Tier-mapped: the schedule names an existing lowered primitive —
    the XLA-native collective, the Pallas
    ``pltpu.make_async_remote_copy`` device kernels (coll/pallas_ring),
    the quantized-wire codec (coll/quant), or the host tiers — and the
    IR is the *documentation + validation contract* for it.

The lowered callable has the ALLREDUCE_ALGOS signature
``fn(x, axis_name, op)`` and composes with coll/framework's
``compile_plan`` (jit(shard_map(...))) like every other tier.

``validate`` is the validity checker: it proves a lowered schedule
bit-identical to the ``ring`` reference tier by running both over
integer-valued payloads (exactly representable at every combine, so
reduction-order differences cannot produce ULP noise) and comparing
raw result bytes. Quantized-wire schedules are validated on
block-constant payloads — the one family the int8 block codec
round-trips exactly — which checks the wiring end-to-end without
conflating it with the codec's documented precision loss.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ...core.errors import ArgumentError
from .ir import ANNOTATIONS, Schedule

#: lowered-callable memo, keyed by schedule digest (table construction
#: is pure python; jit caching happens downstream in compile_plan).
_LOWERED: dict[str, Callable] = {}


def _round_tables(sched: Schedule) -> list[tuple]:
    """Per-round constant tables: (perm, send_chunk, recv_mode,
    recv_chunk) with recv_mode 0=idle, 1=reduce, 2=copy."""
    n = sched.nranks
    by_round: dict[int, list] = {}
    for s in sched.steps:
        if s.kind in ANNOTATIONS:
            continue
        by_round.setdefault(s.round, []).append(s)
    tables = []
    for rnd in sorted(by_round):
        perm: list[tuple[int, int]] = []
        send_chunk = [0] * n
        recv_mode = [0] * n
        recv_chunk = [0] * n
        for s in by_round[rnd]:
            if s.kind == "send":
                perm.append((s.rank, s.peer))
                send_chunk[s.rank] = s.chunk
            else:
                recv_mode[s.rank] = 1 if s.kind == "reduce" else 2
                recv_chunk[s.rank] = s.chunk
        tables.append((tuple(perm), np.asarray(send_chunk, np.int32),
                       np.asarray(recv_mode, np.int32),
                       np.asarray(recv_chunk, np.int32)))
    return tables


def _lower_interpret(sched: Schedule) -> Callable:
    """Compile the step program into a traced round loop."""
    tables = _round_tables(sched)
    nranks, nchunks = sched.nranks, sched.nchunks

    def run(x, axis_name: str, op):
        import jax.numpy as jnp
        from jax import lax

        from .. import spmd

        n = lax.axis_size(axis_name)
        if n != nranks:
            raise ArgumentError(
                f"schedule {sched.name!r} compiled for {nranks} ranks, "
                f"axis {axis_name!r} has {n}"
            )
        rank = lax.axis_index(axis_name)
        flat, total = spmd._flatten_pad(x, nchunks)
        state = flat.reshape(nchunks, -1)
        for perm, send_chunk, recv_mode, recv_chunk in tables:
            sidx = jnp.take(jnp.asarray(send_chunk), rank)
            val = jnp.take(state, sidx, axis=0)
            recvd = lax.ppermute(val, axis_name, list(perm))
            mode = jnp.take(jnp.asarray(recv_mode), rank)
            ridx = jnp.take(jnp.asarray(recv_chunk), rank)
            cur = jnp.take(state, ridx, axis=0)
            new = jnp.where(mode == 1, op.combine(recvd, cur),
                            jnp.where(mode == 2, recvd, cur))
            state = state.at[ridx].set(new)
        return state.reshape(-1)[:total].reshape(x.shape)

    return run


def _lower_primitive(sched: Schedule) -> Callable:
    """Map the schedule to an already-lowered tier entry point."""
    prim = sched.meta.get("primitive", "")
    if prim == "native":
        from .. import spmd

        return spmd.allreduce_native
    if prim == "gather_reduce":
        from .. import spmd

        return spmd._allreduce_gather_reduce
    if prim == "quant_ring":
        from .. import quant

        wire = sched.meta.get("wire")
        block = sched.meta.get("block")

        def _quant_ring(x, axis_name, op):
            # the schedule pins the wire/block it was generated (and
            # validated/tuned) for; cvars only fill the gaps
            return quant.allreduce_quant_ring(x, axis_name, op,
                                              wire=wire, block=block)

        return _quant_ring
    if prim == "quant_pallas":
        from .. import quant

        return quant.allreduce_block_quant
    if prim == "pallas_ring":
        from .. import pallas_ring

        return pallas_ring.allreduce_block
    raise ArgumentError(
        f"schedule {sched.name!r} names unknown primitive {prim!r}"
    )


def lower(sched: Schedule) -> Callable:
    """Schedule -> callable with the ALLREDUCE_ALGOS signature.
    Memoized on the schedule digest; emits one ``sched.compile`` trace
    instant per actual lowering."""
    key = sched.digest()
    fn = _LOWERED.get(key)
    if fn is not None:
        return fn
    if sched.meta.get("lowering", "interpret") == "primitive":
        fn = _lower_primitive(sched)
    else:
        fn = _lower_interpret(sched)
    _LOWERED[key] = fn
    from ...trace import span as tspan

    tspan.instant("sched.compile", cat="sched", schedule=sched.name,
                  nranks=sched.nranks, rounds=sched.rounds(),
                  lowering=sched.meta.get("lowering", "interpret"),
                  digest=key)
    return fn


def clear_lowered() -> None:
    """Forget memoized lowerings (tests / re-init)."""
    _LOWERED.clear()


# ---------------------------------------------------------------------------
# validity checker
# ---------------------------------------------------------------------------

def _payload(nranks: int, nelems: int, dtype, *,
             block_constant: bool) -> np.ndarray:
    """Power-of-two payload ({1, 2}), exactly representable in every
    supported dtype under every reduction order AND every op: sums over
    8 ranks top out at 16, products at 256 = 2^8 — both exact in bf16,
    f16, f32 and every int type, so a schedule that combines in a
    different order than the ring reference still lands on the same
    bits. ``block_constant`` makes each rank's buffer one constant —
    the family the int8 block-scaled codec round-trips exactly
    (scale=v/127, q=±127)."""
    rng = np.random.default_rng(0xC011)
    if block_constant:
        per_rank = 2 ** rng.integers(0, 2, size=(nranks, 1))
        data = np.broadcast_to(per_rank, (nranks, nelems)).copy()
    else:
        data = 2 ** rng.integers(0, 2, size=(nranks, nelems))
    return data.astype(dtype)


def validate(comm, fn: Callable, op, dtype, *, nelems: int = 192,
             label: str = "candidate",
             block_constant: bool = False,
             check_vma: bool = True) -> bool:
    """Bit-identical check of ``fn`` against the ring reference tier on
    ``comm``. True when every result byte matches."""
    import jax

    from ..framework import compile_plan
    from .. import spmd
    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    data = _payload(comm.size, nelems, dtype,
                    block_constant=block_constant)
    x = comm.put_rank_major(data)
    ref_key = ("sched.validate.ref", op.cache_key, str(np.dtype(dtype)),
               x.shape)
    ref_plan = compile_plan(
        comm, ref_key, lambda b: spmd.allreduce_ring(b, "ranks", op))
    got_key = ("sched.validate", label, op.cache_key,
               str(np.dtype(dtype)), x.shape)
    got_plan = compile_plan(comm, got_key,
                            lambda b: fn(b, "ranks", op),
                            check_vma=check_vma)
    ref = np.asarray(jax.device_get(ref_plan(x)))
    got = np.asarray(jax.device_get(got_plan(x)))
    return ref.dtype == got.dtype and ref.shape == got.shape \
        and ref.tobytes() == got.tobytes()


def _validate_bounded(comm, fn: Callable, op, dtype, *, wire, block,
                      nelems: int, label: str) -> bool:
    """Lossy-tier validity: result within coll/quant's analytic
    worst-case error bound of the ring reference, elementwise."""
    import jax

    from ..framework import compile_plan
    from .. import quant, spmd
    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    data = _payload(comm.size, nelems, dtype, block_constant=False)
    x = comm.put_rank_major(data)
    ref_plan = compile_plan(
        comm, ("sched.validate.ref", op.cache_key, str(np.dtype(dtype)),
               x.shape),
        lambda b: spmd.allreduce_ring(b, "ranks", op))
    got_plan = compile_plan(
        comm, ("sched.validate", label, op.cache_key,
               str(np.dtype(dtype)), x.shape),
        lambda b: fn(b, "ranks", op))
    ref = np.asarray(jax.device_get(ref_plan(x)), np.float64)
    got = np.asarray(jax.device_get(got_plan(x)), np.float64)
    bound = np.asarray(jax.device_get(
        quant.analytic_error_bound(data, wire=wire, block=block)),
        np.float64)
    return ref.shape == got.shape and bool(
        np.all(np.abs(ref - got) <= bound[None, :] + 1e-12))


def validate_schedule(comm, sched: Schedule, op, dtype, *,
                      nelems: int = 192) -> bool:
    """Validity check for a lowered Schedule.

    Exact tiers (everything but the int8 quantized wire) must be
    BIT-IDENTICAL to the ring reference — the power-of-two payload
    family makes every reduction order exact, so any deviation is a
    compiler bug, not float noise. The bf16 quantized wire is held to
    the same bar: its hop path is pure casts and adds (no division),
    exact on small integers. The int8 wire is lossy by design — its
    scale arithmetic (max/127) is not even stable across XLA fusion
    choices — so it validates against coll/quant's analytic worst-case
    error bound instead, the same contract quant's own tests enforce."""
    quantized = sched.meta.get("primitive", "").startswith("quant") \
        or any(s.kind in ANNOTATIONS for s in sched.steps)
    if quantized and sched.meta.get("wire", "int8") != "bf16":
        return _validate_bounded(
            comm, lower(sched), op, dtype,
            wire=sched.meta.get("wire", "int8"),
            block=sched.meta.get("block"), nelems=nelems,
            label=f"sched:{sched.digest()}")
    is_pallas = "pallas" in sched.meta.get("primitive", "")
    return validate(
        comm, lower(sched), op, dtype, nelems=nelems,
        label=f"sched:{sched.digest()}",
        check_vma=not is_pallas,
    )


__all__ = ["clear_lowered", "lower", "validate", "validate_schedule"]

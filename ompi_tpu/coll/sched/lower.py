"""Lowering: compile a Schedule IR program to a fused jitted callable.

Three lowering modes, selected by ``Schedule.meta["lowering"]`` (or
the explicit ``lower(sched, strategy=...)`` override):

``interpret``
    A genuine IR executor: the step program is compiled round-by-round
    into a traced jax program — each round becomes one
    ``lax.ppermute`` (the ICI DMA) driven by per-round index tables
    (who sends which chunk where, who reduces/copies what), the
    reduction is the Op's combine on the VPU/MXU. The tables are
    python-side constants, so the whole schedule unrolls into the XLA
    graph exactly like the hand-written spmd algorithms — and XLA
    fuses/overlaps the rounds of independent chunk chains (segmented
    ring) for free.

``primitive``
    Tier-mapped: the schedule names an existing lowered primitive —
    the XLA-native collective, the Pallas
    ``pltpu.make_async_remote_copy`` device kernels (coll/pallas_ring),
    the quantized-wire codec (coll/quant), or the host tiers — and the
    IR is the *documentation + validation contract* for it.

``pallas``
    Compiled: the step program itself is lowered into one fused
    ``make_async_remote_copy`` kernel (sched/pallas_lower.py) — every
    round a remote DMA overlapped with the combine, double-buffered
    chunk slots sized from the IR's chunk plan. The ``device_pallas``
    lattice tier.

The lowered callable has the ALLREDUCE_ALGOS signature
``fn(x, axis_name, op)`` and composes with coll/framework's
``compile_plan`` (jit(shard_map(...))) like every other tier.

``validate`` is the validity checker: it proves a lowered schedule
bit-identical to the ``ring`` reference tier by running both over
integer-valued payloads (exactly representable at every combine, so
reduction-order differences cannot produce ULP noise) and comparing
raw result bytes. Quantized-wire schedules are validated on
block-constant payloads — the one family the int8 block codec
round-trips exactly — which checks the wiring end-to-end without
conflating it with the codec's documented precision loss.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ...core.errors import ArgumentError
from .ir import ANNOTATIONS, Schedule

#: lowered-callable memo, keyed by (schedule digest, strategy): the
#: digest covers meta["lowering"], but the explicit strategy override
#: must not collide with the meta-selected lowering of the same
#: program (table construction is pure python; jit caching happens
#: downstream in compile_plan).
_LOWERED: dict[tuple, Callable] = {}

#: The three lowering strategies, in maturity order.
STRATEGIES = ("interpret", "primitive", "pallas")


def _round_tables(sched: Schedule) -> list[tuple]:
    """Per-round constant tables: (perm, send_chunk, recv_mode,
    recv_chunk) with recv_mode 0=idle, 1=reduce, 2=copy."""
    n = sched.nranks
    by_round: dict[int, list] = {}
    for s in sched.steps:
        if s.kind in ANNOTATIONS:
            continue
        by_round.setdefault(s.round, []).append(s)
    tables = []
    for rnd in sorted(by_round):
        perm: list[tuple[int, int]] = []
        send_chunk = [0] * n
        recv_mode = [0] * n
        recv_chunk = [0] * n
        for s in by_round[rnd]:
            if s.kind == "send":
                perm.append((s.rank, s.peer))
                send_chunk[s.rank] = s.chunk
            else:
                recv_mode[s.rank] = 1 if s.kind == "reduce" else 2
                recv_chunk[s.rank] = s.chunk
        tables.append((tuple(perm), np.asarray(send_chunk, np.int32),
                       np.asarray(recv_mode, np.int32),
                       np.asarray(recv_chunk, np.int32)))
    return tables


def _lower_interpret(sched: Schedule) -> Callable:
    """Compile the step program into a traced round loop."""
    tables = _round_tables(sched)
    nranks, nchunks = sched.nranks, sched.nchunks

    def run(x, axis_name: str, op):
        import jax.numpy as jnp
        from jax import lax

        from .. import spmd

        n = lax.axis_size(axis_name)
        if n != nranks:
            raise ArgumentError(
                f"schedule {sched.name!r} compiled for {nranks} ranks, "
                f"axis {axis_name!r} has {n}"
            )
        rank = lax.axis_index(axis_name)
        flat, total = spmd._flatten_pad(x, nchunks)
        state = flat.reshape(nchunks, -1)
        for perm, send_chunk, recv_mode, recv_chunk in tables:
            sidx = jnp.take(jnp.asarray(send_chunk), rank)
            val = jnp.take(state, sidx, axis=0)
            recvd = lax.ppermute(val, axis_name, list(perm))
            mode = jnp.take(jnp.asarray(recv_mode), rank)
            ridx = jnp.take(jnp.asarray(recv_chunk), rank)
            cur = jnp.take(state, ridx, axis=0)
            new = jnp.where(mode == 1, op.combine(recvd, cur),
                            jnp.where(mode == 2, recvd, cur))
            state = state.at[ridx].set(new)
        return state.reshape(-1)[:total].reshape(x.shape)

    return run


def _lower_primitive(sched: Schedule) -> Callable:
    """Map the schedule to an already-lowered tier entry point."""
    prim = sched.meta.get("primitive", "")
    if prim == "native":
        from .. import spmd

        return spmd.allreduce_native
    if prim == "gather_reduce":
        from .. import spmd

        return spmd._allreduce_gather_reduce
    if prim == "quant_ring":
        from .. import quant

        wire = sched.meta.get("wire")
        block = sched.meta.get("block")

        def _quant_ring(x, axis_name, op):
            # the schedule pins the wire/block it was generated (and
            # validated/tuned) for; cvars only fill the gaps
            return quant.allreduce_quant_ring(x, axis_name, op,
                                              wire=wire, block=block)

        return _quant_ring
    if prim == "quant_pallas":
        from .. import quant

        return quant.allreduce_block_quant
    if prim == "pallas_ring":
        from .. import pallas_ring

        return pallas_ring.allreduce_block
    raise ArgumentError(
        f"schedule {sched.name!r} names unknown primitive {prim!r}"
    )


def lower(sched: Schedule, strategy: Optional[str] = None) -> Callable:
    """Schedule -> callable with the registered-algo signature
    (ALLREDUCE_ALGOS for allreduce programs, REDUCE_SCATTER_ALGOS for
    reduce-scatter ones). ``strategy`` overrides the schedule's own
    ``meta["lowering"]`` directive. Memoized on (digest, strategy);
    emits one ``sched.compile`` trace instant per actual lowering and
    counts every selection in the per-strategy SPC counters (the
    ``sched_lower_strategy_total`` telemetry series)."""
    if strategy is None:
        strategy = sched.meta.get("lowering", "interpret")
        if strategy not in STRATEGIES:
            strategy = "interpret"
    elif strategy not in STRATEGIES:
        raise ArgumentError(
            f"unknown lowering strategy {strategy!r}; known: "
            f"{list(STRATEGIES)}")
    from ...core.counters import SPC

    SPC.record(f"sched_lower_strategy_{strategy}")
    key = (sched.digest(), strategy)
    fn = _LOWERED.get(key)
    if fn is not None:
        return fn
    if strategy == "primitive":
        fn = _lower_primitive(sched)
    elif strategy == "pallas":
        from . import pallas_lower

        fn = pallas_lower.compile_schedule(sched)
    else:
        fn = _lower_interpret(sched)
    _LOWERED[key] = fn
    from ...trace import span as tspan

    tspan.instant("sched.compile", cat="sched", schedule=sched.name,
                  nranks=sched.nranks, rounds=sched.rounds(),
                  lowering=strategy, digest=key[0])
    return fn


def clear_lowered() -> None:
    """Forget memoized lowerings (tests / re-init)."""
    _LOWERED.clear()
    from . import pallas_lower

    pallas_lower.clear_compiled()


# ---------------------------------------------------------------------------
# validity checker
# ---------------------------------------------------------------------------

def _payload(nranks: int, nelems: int, dtype, *,
             block_constant: bool) -> np.ndarray:
    """Power-of-two payload ({1, 2}), exactly representable in every
    supported dtype under every reduction order AND every op: sums over
    8 ranks top out at 16, products at 256 = 2^8 — both exact in bf16,
    f16, f32 and every int type, so a schedule that combines in a
    different order than the ring reference still lands on the same
    bits. ``block_constant`` makes each rank's buffer one constant —
    the family the int8 block-scaled codec round-trips exactly
    (scale=v/127, q=±127)."""
    rng = np.random.default_rng(0xC011)
    if block_constant:
        per_rank = 2 ** rng.integers(0, 2, size=(nranks, 1))
        data = np.broadcast_to(per_rank, (nranks, nelems)).copy()
    else:
        data = 2 ** rng.integers(0, 2, size=(nranks, nelems))
    return data.astype(dtype)


def validate(comm, fn: Callable, op, dtype, *, nelems: int = 192,
             label: str = "candidate",
             block_constant: bool = False,
             check_vma: bool = True) -> bool:
    """Bit-identical check of ``fn`` against the ring reference tier on
    ``comm``. True when every result byte matches."""
    import jax

    from ..framework import compile_plan
    from .. import spmd
    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    data = _payload(comm.size, nelems, dtype,
                    block_constant=block_constant)
    x = comm.put_rank_major(data)
    ref_key = ("sched.validate.ref", op.cache_key, str(np.dtype(dtype)),
               x.shape)
    ref_plan = compile_plan(
        comm, ref_key, lambda b: spmd.allreduce_ring(b, "ranks", op))
    got_key = ("sched.validate", label, op.cache_key,
               str(np.dtype(dtype)), x.shape)
    got_plan = compile_plan(comm, got_key,
                            lambda b: fn(b, "ranks", op),
                            check_vma=check_vma)
    ref = np.asarray(jax.device_get(ref_plan(x)))
    got = np.asarray(jax.device_get(got_plan(x)))
    return ref.dtype == got.dtype and ref.shape == got.shape \
        and ref.tobytes() == got.tobytes()


def _validate_bounded(comm, fn: Callable, op, dtype, *, wire, block,
                      nelems: int, label: str) -> bool:
    """Lossy-tier validity: result within coll/quant's analytic
    worst-case error bound of the ring reference, elementwise."""
    import jax

    from ..framework import compile_plan
    from .. import quant, spmd
    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    data = _payload(comm.size, nelems, dtype, block_constant=False)
    x = comm.put_rank_major(data)
    ref_plan = compile_plan(
        comm, ("sched.validate.ref", op.cache_key, str(np.dtype(dtype)),
               x.shape),
        lambda b: spmd.allreduce_ring(b, "ranks", op))
    got_plan = compile_plan(
        comm, ("sched.validate", label, op.cache_key,
               str(np.dtype(dtype)), x.shape),
        lambda b: fn(b, "ranks", op))
    ref = np.asarray(jax.device_get(ref_plan(x)), np.float64)
    got = np.asarray(jax.device_get(got_plan(x)), np.float64)
    bound = np.asarray(jax.device_get(
        quant.analytic_error_bound(data, wire=wire, block=block)),
        np.float64)
    return ref.shape == got.shape and bool(
        np.all(np.abs(ref - got) <= bound[None, :] + 1e-12))


def _validate_reduce_scatter(comm, fn: Callable, op, dtype, *,
                             nelems: int, label: str,
                             check_vma: bool = True) -> bool:
    """Bit-identical check of a reduce-scatter callable (input: the
    local (n, chunk) contribution view; output: the own reduced block)
    against the ring reference ``spmd.reduce_scatter_ring``."""
    import jax

    from ..framework import compile_plan
    from .. import spmd
    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    n = comm.size
    data = _payload(n, n * nelems, dtype,
                    block_constant=False).reshape(n, n, nelems)
    x = comm.put_rank_major(data)
    # shard_map hands each rank a (1, n, nelems) slice; the [0]/[None]
    # bracket keeps the P("ranks") in/out specs.
    ref_plan = compile_plan(
        comm, ("sched.validate.rs_ref", op.cache_key,
               str(np.dtype(dtype)), x.shape),
        lambda b: spmd.reduce_scatter_ring(b[0], "ranks", op)[None])
    got_plan = compile_plan(
        comm, ("sched.validate", label, op.cache_key,
               str(np.dtype(dtype)), x.shape),
        lambda b: fn(b[0], "ranks", op)[None], check_vma=check_vma)
    ref = np.asarray(jax.device_get(ref_plan(x)))
    got = np.asarray(jax.device_get(got_plan(x)))
    return ref.dtype == got.dtype and ref.shape == got.shape \
        and ref.tobytes() == got.tobytes()


def _pallas_executable() -> bool:
    """Can a Mosaic pallas_call actually run here — real TPU, or a jax
    build whose interpret mode can emulate the remote DMA/semaphore
    primitives on CPU? jax 0.4.x ships the primitives without the
    emulation, so tier-1 there validates pallas codegen through the
    table-program simulator instead."""
    import jax

    from .. import pallas_ring

    return jax.default_backend() == "tpu" \
        or pallas_ring.interpret_available()


def _validate_simulated(comm, sched: Schedule, op, dtype, *,
                        nelems: int) -> bool:
    """Bit-identity check of a pallas-lowered schedule through
    ``pallas_lower.simulate`` — the sequential executor that shares the
    kernel's table program, slot discipline and store gating — against
    the mathematical reduction (exact for the power-of-two payloads
    regardless of combine order). Covers every decision ``analyze``
    bakes into the kernel when Mosaic execution is unavailable."""
    import functools

    import jax.numpy as jnp

    from . import pallas_lower
    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    n = sched.nranks
    if comm.size != n:
        raise ArgumentError(
            f"schedule {sched.name!r} compiled for {n} ranks, comm has "
            f"{comm.size}")
    data = jnp.asarray(
        _payload(n, sched.nchunks * nelems, dtype,
                 block_constant=False).reshape(n, sched.nchunks, nelems))
    got = np.asarray(pallas_lower.simulate(sched, data, op))
    red = functools.reduce(op.combine, [data[k] for k in range(n)])
    if sched.op == "reduce_scatter":
        # REDUCE_SCATTER_ALGOS contract: rank k's result is chunk k. A
        # schedule that lands a different chunk fails right here.
        ref = np.asarray(jnp.stack([red[k] for k in range(n)]))
    else:
        ref = np.asarray(jnp.stack([red] * n))
    return ref.dtype == got.dtype and ref.shape == got.shape \
        and ref.tobytes() == got.tobytes()


#: Primitives whose lowered callable contains a Mosaic pallas_call.
_MOSAIC_PRIMITIVES = ("pallas_ring", "quant_pallas")


def _needs_vma_exemption(sched: Schedule) -> bool:
    """True only when the lowered callable actually invokes a Mosaic
    ``pallas_call``: its outputs mix varying and replicated values in a
    way jax's vma tracking rejects, so those plans compile with
    ``check_vma=False`` (jax's documented workaround — see
    framework.compile_plan). Scoped to the known Mosaic primitives and
    the pallas lowering strategy, not any name containing "pallas", so
    every other schedule keeps full vma checking."""
    return sched.meta.get("primitive", "") in _MOSAIC_PRIMITIVES \
        or sched.meta.get("lowering") == "pallas"


def validate_schedule(comm, sched: Schedule, op, dtype, *,
                      nelems: int = 192) -> bool:
    """Validity check for a lowered Schedule.

    Exact tiers (everything but the int8 quantized wire) must be
    BIT-IDENTICAL to the ring reference — the power-of-two payload
    family makes every reduction order exact, so any deviation is a
    compiler bug, not float noise. The bf16 quantized wire is held to
    the same bar: its hop path is pure casts and adds (no division),
    exact on small integers. The int8 wire is lossy by design — its
    scale arithmetic (max/127) is not even stable across XLA fusion
    choices — so it validates against coll/quant's analytic worst-case
    error bound instead, the same contract quant's own tests enforce.

    Pallas-lowered and Mosaic-primitive schedules are held to the same
    bit-identity bar on every dtype (bf16 included); only the vma
    *plan check* is exempted for them (``_needs_vma_exemption``) — the
    byte comparison itself never is. When the pallas kernel cannot
    execute at all (CPU on a jax build without Mosaic interpret mode —
    ``_pallas_executable``), the check runs through the table-program
    simulator, which preserves the bit-identity bar on the codegen."""
    quantized = sched.meta.get("primitive", "").startswith("quant") \
        or any(s.kind in ANNOTATIONS for s in sched.steps)
    if quantized and sched.meta.get("wire", "int8") != "bf16":
        return _validate_bounded(
            comm, lower(sched), op, dtype,
            wire=sched.meta.get("wire", "int8"),
            block=sched.meta.get("block"), nelems=nelems,
            label=f"sched:{sched.digest()}")
    if sched.meta.get("lowering") == "pallas" and not _pallas_executable():
        return _validate_simulated(comm, sched, op, dtype, nelems=nelems)
    check_vma = not _needs_vma_exemption(sched)
    if sched.op == "reduce_scatter":
        return _validate_reduce_scatter(
            comm, lower(sched), op, dtype, nelems=nelems,
            label=f"sched:{sched.digest()}", check_vma=check_vma)
    return validate(
        comm, lower(sched), op, dtype, nelems=nelems,
        label=f"sched:{sched.digest()}",
        check_vma=check_vma,
    )


__all__ = ["STRATEGIES", "clear_lowered", "lower", "validate",
           "validate_schedule"]

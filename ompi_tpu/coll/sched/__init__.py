"""coll/sched: the schedule compiler.

Collective algorithm choice as a compiler pipeline instead of an
if-ladder:

- ``ir``        declarative chunk/step programs (Schedule) + generators
                (ring, recursive doubling, segmented ring, hierarchical,
                quantized wire) parameterized by topology
- ``lower``     Schedule -> fused jitted callable, plus the validity
                checker (bit-identical vs the ring reference tier)
- ``lattice``   the algorithm/tier/fallback lattice (breaker + health
                derive from it; routing = deny-set walk)
- ``priors``    the static cold-start decision tables
- ``cache``     versioned on-disk winner cache (fleet warms once)
- ``autotune``  the candidate sweep that fills the cache

This package module is import-light (ir + lattice only); everything
that touches jax, config, or the filesystem loads lazily through the
functions below. ``lookup`` is the dispatch-path entry: tuned's
decide_* consult it first and fall back to the priors only on a cache
miss.
"""

from __future__ import annotations

from typing import Optional

from . import ir, lattice
from .ir import Schedule, ScheduleError

#: (algo, nranks) -> built Schedule (construction is pure python; the
#: lowering memo in lower.py is keyed by digest underneath this).
_SCHED_MEMO: dict = {}

#: Algorithms this package registers into tuned.ALLREDUCE_ALGOS. The
#: sched_pallas_* names are the same IR programs lowered to fused
#: Mosaic kernels (sched/pallas_lower) — the device_pallas tier.
ALGOS = ("sched_ring", "sched_rd", "sched_ring_seg", "sched_hier",
         "sched_quant", "sched_pallas_ring", "sched_pallas_ring_seg")


# ---------------------------------------------------------------------------
# topology-aware schedule construction
# ---------------------------------------------------------------------------

def _topo_order(nranks: int) -> Optional[list]:
    """ICI-aware ring order when the live mesh matches ``nranks``
    (identity/None otherwise — e.g. CPU meshes or sub-communicators)."""
    try:
        from ...runtime import mesh

        procs = mesh.discover()
        if len(procs) == nranks:
            return mesh.ring_order(procs)
    except Exception:  # commlint: allow(broadexcept)
        pass
    return None


def _host_groups(nranks: int) -> list:
    """Host-grouped rank partition for the hierarchical schedule;
    a single group when the live mesh doesn't match ``nranks``."""
    try:
        from ...runtime import mesh

        procs = mesh.discover()
        if len(procs) == nranks:
            groups = [sorted(p.rank for p in g)
                      for _h, g in sorted(mesh.hosts_of(procs).items())]
            if sum(len(g) for g in groups) == nranks:
                return groups
    except Exception:  # commlint: allow(broadexcept)
        pass
    return [list(range(nranks))]


def build_schedule(algo: str, nranks: int, *, segments: int = 2,
                   groups=None) -> Schedule:
    """Build (memoized) the Schedule behind a registered sched_* name,
    enriched with live topology (ring order, host groups) when the
    mesh matches."""
    from . import retune

    # the straggler-penalty state is part of the program: a reroot or
    # segment change must rebuild, not hit the memo
    key = (algo, nranks, segments,
           tuple(map(tuple, groups)) if groups else None,
           retune.penalty_stamp())
    if algo == "sched_quant":
        from .. import quant

        # the wire codec is part of the program; a cvar flip must
        # rebuild, not hit the memo
        key = key + (quant._wire_var.value, quant._block_var.value)
    sch = _SCHED_MEMO.get(key)
    if sch is not None:
        return sch
    if algo == "sched_ring":
        sch = ir.ring(nranks, order=_topo_order(nranks))
    elif algo == "sched_rd":
        if nranks & (nranks - 1):
            # degrade like tuned's pallas_rd guard: a rules file naming
            # rd on a non-power-of-two world gets the ring, not a trace
            # error
            sch = ir.ring(nranks, order=_topo_order(nranks))
        else:
            sch = ir.recursive_doubling(nranks)
    elif algo == "sched_ring_seg":
        sch = ir.segmented_ring(nranks,
                                retune.effective_segments(segments),
                                order=_topo_order(nranks))
    elif algo == "sched_hier":
        sch = ir.hierarchical(
            retune.reroot_groups(groups or _host_groups(nranks)))
    elif algo == "sched_quant":
        from .. import quant

        sch = ir.quantized_wire(nranks, quant._wire_var.value,
                                quant._block_var.value,
                                order=_topo_order(nranks))
    elif algo == "sched_pallas_ring":
        sch = ir.with_lowering(
            ir.ring(nranks, order=_topo_order(nranks)), "pallas",
            tier="device_pallas")
    elif algo == "sched_pallas_ring_seg":
        sch = ir.with_lowering(
            ir.segmented_ring(nranks,
                              retune.effective_segments(segments),
                              order=_topo_order(nranks)), "pallas",
            tier="device_pallas")
    elif algo == "sched_pallas_rs":
        sch = ir.with_lowering(
            ir.reduce_scatter(nranks, order=_topo_order(nranks)),
            "pallas", tier="device_pallas")
    else:
        raise ScheduleError(f"unknown sched algorithm {algo!r}; "
                            f"known: {list(ALGOS)}")
    _SCHED_MEMO[key] = sch
    return sch


def clear_schedules() -> None:
    """Forget built schedules and lowerings (tests / re-init)."""
    from . import lower as _lower

    _SCHED_MEMO.clear()
    _lower.clear_lowered()


# ---------------------------------------------------------------------------
# registered algorithm wrappers (ALLREDUCE_ALGOS signature)
# ---------------------------------------------------------------------------

def _run(algo: str, x, axis_name: str, op):
    from jax import lax

    from . import lower as _lower

    sch = build_schedule(algo, lax.axis_size(axis_name))
    return _lower.lower(sch)(x, axis_name, op)


def allreduce_sched_ring(x, axis_name, op):
    return _run("sched_ring", x, axis_name, op)


def allreduce_sched_rd(x, axis_name, op):
    return _run("sched_rd", x, axis_name, op)


def allreduce_sched_ring_seg(x, axis_name, op):
    return _run("sched_ring_seg", x, axis_name, op)


def allreduce_sched_hier(x, axis_name, op):
    return _run("sched_hier", x, axis_name, op)


def allreduce_sched_quant(x, axis_name, op):
    return _run("sched_quant", x, axis_name, op)


def allreduce_sched_pallas_ring(x, axis_name, op):
    return _run("sched_pallas_ring", x, axis_name, op)


def allreduce_sched_pallas_ring_seg(x, axis_name, op):
    return _run("sched_pallas_ring_seg", x, axis_name, op)


def reduce_scatter_sched_pallas(x, axis_name, op):
    """REDUCE_SCATTER_ALGOS signature: x is the local (nranks, chunk)
    contribution view, the result the own reduced block."""
    return _run("sched_pallas_rs", x, axis_name, op)


# ---------------------------------------------------------------------------
# dispatch-path cache consult
# ---------------------------------------------------------------------------

def _usable(opname: str, algo: str, dtype, op) -> bool:
    """Is a cached winner selectable right now? Guards the cases where
    the cache was tuned under settings the current call doesn't meet
    (quant consent/support, unknown algorithm after a version skew)."""
    from .. import tuned

    if algo not in tuned._algo_space(opname) and algo not in ALGOS:
        return False
    if tuned.is_quant_algo(algo) or algo == "sched_quant":
        from .. import quant

        if not quant._enable_var.value:
            return False
        if not quant.supports(op or "sum", dtype):
            return False
    return True


def lookup(opname: str, nbytes_per_rank: int, nranks: int, dtype=None,
           op=None, scope: Optional[str] = None) -> Optional[str]:
    """The compiled-schedule cache consult. Returns the tuned winner's
    algorithm name, or None (miss / disabled / unusable winner) — the
    caller then falls back to the static priors. Emits
    sched.cache_hit/sched.cache_miss instants and the matching SPC
    counters; misses are only counted once the cache is active so an
    untuned fleet doesn't drown monitoring in miss noise. With an SLO
    target in force for ``scope`` the winner is replaced by the
    cheapest-wire frontier point meeting the target (slo.py)."""
    from . import autotune, cache as _cache

    if not _cache._enable_var.value:
        return None
    fp = autotune.fingerprint()
    _cache.CACHE.ensure_loaded(fp, nranks)
    if not _cache.CACHE.active():
        return None
    from ...core.counters import SPC
    from ...trace import span as tspan

    key = _cache.cache_key(opname, nbytes_per_rank, nranks, dtype, fp)
    ent = _cache.CACHE.get(key)
    if ent is None:
        SPC.record("sched_cache_misses")
        tspan.instant("sched.cache_miss", cat="sched", key=key)
        return None
    algo = ent.get("algorithm", "")
    if not _usable(opname, algo, dtype, op):
        SPC.record("sched_cache_misses")
        tspan.instant("sched.cache_miss", cat="sched", key=key,
                      algo=algo, reason="unusable")
        return None
    SPC.record("sched_cache_hits")
    tspan.instant("sched.cache_hit", cat="sched", key=key, algo=algo)
    from . import slo

    target = slo.target_for(scope)
    if target > 0:
        pick = slo.frontier_pick(ent, target)
        if pick and pick != algo and _usable(opname, pick, dtype, op):
            SPC.record("sched_slo_frontier_picks")
            tspan.instant("sched.slo_pick", cat="sched", key=key,
                          algo=pick, winner=algo, target_us=target)
            return pick
    return algo


def warm(nranks: int, **kw) -> dict:
    """Offline cache warm: run the autotuner (model mode by default —
    no devices needed) and persist winners to the default path. The
    tools/sched CLI front-ends this."""
    from . import autotune

    return autotune.tune(nranks, **kw)


__all__ = [
    "ALGOS", "Schedule", "ScheduleError", "allreduce_sched_hier",
    "allreduce_sched_pallas_ring", "allreduce_sched_pallas_ring_seg",
    "allreduce_sched_quant", "allreduce_sched_rd",
    "allreduce_sched_ring", "allreduce_sched_ring_seg",
    "build_schedule", "clear_schedules", "ir", "lattice", "lookup",
    "reduce_scatter_sched_pallas", "warm",
]

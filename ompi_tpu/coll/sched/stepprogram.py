"""coll/sched/stepprogram — the training step as the compilation unit.

PR 15's overlap session drove one ``PartitionedAllreduce`` per bucket
from Python: B independent collectives, B progress callbacks, B
broadcast tails — and an autotuner that could only see one collective
at a time. This module promotes the WHOLE step to the sched layer (the
GC3 idea: compile the communication *program*, not the call):

* :func:`compile_step` turns the step's bucket list into one
  :class:`~ompi_tpu.coll.sched.ir.Program` — a named sub-collective per
  bucket, ZeRO-style reduce-scatter + allgather pairs as first-class
  node pairs with an explicit readiness dependency, per-bucket tile
  geometry resolved through the autotuner's program-level precedence
  (caller > winner cache > deterministic model), and a cross-bucket
  interleave order. Everything that decides what executes lands in the
  program meta, so ``Program.digest()`` is byte-identical across
  same-seed controllers — the same contract the winner cache carries.
* Dense round-uniform node groups additionally fuse through the PR 14
  Pallas backend (:func:`~.pallas_lower.fuse_schedules`): a step's
  ring allreduces become ONE chained table program — a handful of
  fused kernels per step instead of one per bucket — validated by the
  table-program simulator oracle on jax builds without TPU interpret.
* :class:`StepExecutor` binds the compiled program to live transport:
  per-node ``PartitionedAllreduce`` flows (the allreduce choice) or
  per-shard flows rooted at the shard owner (the RS/AG choice), armed
  in interleave order inside one dispatch window, drained by ONE
  merged progress callback, and finished with ONE merged broadcast per
  root instead of one per bucket.

The overlap session (parallel/overlap) binds one executor and feeds it
readiness events; it no longer constructs per-bucket collectives
itself (the ``stepprogram`` lint rule keeps it that way).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from ...core import progress as _progress
from ...core.counters import SPC
from ...core.errors import ArgumentError, RequestError
from ...part.framework import block_range
from . import autotune as _autotune
from . import ir
from . import pallas_lower as _pallas


@dataclass(frozen=True)
class NodePlan:
    """Executable decisions for one bucket of the compiled step."""

    bucket: int
    name: str
    choice: str        # "allreduce" | "rs_ag" | "rs_resident"
    elems: int
    dtype: Any         # np.dtype
    tile_bytes: int
    tile_elems: int
    tiles: int
    tile_source: str   # "caller" | "cache" | "model"
    #: forward-consume deadline behind an "rs_ag"/"rs_resident" choice
    #: (slipstream); None when the caller supplied no deadlines.
    ag_deadline: Optional[int] = None


@dataclass(frozen=True)
class CompiledStep:
    """One training step's comm, compiled: the IR program (digest =
    identity), the per-bucket execution plan, the arm order, and the
    fused Pallas-lowerable schedules."""

    program: ir.Program
    nodes: tuple       # NodePlan per bucket
    interleave: tuple  # bucket indices in arm order (biggest first)
    fused: dict = field(default_factory=dict)  # op -> fused Schedule
    nranks: int = 0
    seed: int = 0
    topo_fp: str = ""
    compile_ms: float = 0.0

    def digest(self) -> str:
        return self.program.digest()


def compile_step(nranks: int, buckets: Sequence, *,
                 tile_bytes=None, seed: Optional[int] = None,
                 topo_fp: Optional[str] = None,
                 node_choices: Optional[Sequence] = None,
                 ag_deadlines: Optional[Sequence] = None,
                 order: Optional[Sequence] = None,
                 name: str = "step") -> CompiledStep:
    """Compile a step's bucket list into one multi-collective program.

    ``buckets`` is a sequence of ``(elems, dtype)`` per-bucket specs
    (rank-major element counts). Per bucket the autotuner resolves the
    tile geometry (caller > winner-cache ``tile_bytes`` > model — no
    silent fallback to a static default) and the
    RS/AG-vs-allreduce schedule decision; ``node_choices`` pins the
    latter per bucket ("allreduce" / "rs_ag" / "rs_resident" / None).
    ``ag_deadlines`` (per bucket, None entries allowed) feeds the
    shard-residency model: a bucket whose owner shard can stay
    resident past its forward-consume deadline compiles to a LONE
    reduce-scatter node — the allgather is elided entirely
    ("rs_resident"), visible in the program digest via the choices
    meta and counted on ``sched_ag_elided_total``. Deterministic:
    same (buckets, nranks, seed, cache state) on any controller yields
    a byte-identical ``Program`` render and digest.
    """
    t0 = time.perf_counter()
    seed = _autotune._seed_var.value if seed is None else int(seed)
    if topo_fp is None:
        topo_fp = _autotune.fingerprint()
    if not buckets:
        raise ArgumentError("compile_step needs at least one bucket")
    specs = [(int(e), np.dtype(str(np.dtype(d)))) for e, d in buckets]
    choices = _autotune.program_choices(
        [e * d.itemsize for e, d in specs], nranks,
        dtypes=[str(d) for _, d in specs], seed=seed, topo_fp=topo_fp,
        tile_bytes=tile_bytes, node_choices=node_choices,
        ag_deadlines=ag_deadlines)
    nodes: list[NodePlan] = []
    prog_nodes: list[ir.ProgramNode] = []
    for i, ((elems, dtype), ch) in enumerate(zip(specs, choices)):
        nbytes = elems * dtype.itemsize
        tb = int(ch["tile_bytes"])
        tiles = max(1, min(-(-nbytes // max(1, tb)), elems))
        tile_elems = -(-elems // tiles)
        tiles = -(-elems // tile_elems)
        choice = ch["choice"]
        if nranks < 2:
            choice = "allreduce"  # degenerate comm: nothing to scatter
        dl = ch.get("ag_deadline")
        nodes.append(NodePlan(
            bucket=i, name=f"b{i}", choice=choice, elems=elems,
            dtype=dtype, tile_bytes=tb, tile_elems=tile_elems,
            tiles=tiles, tile_source=ch["tile_source"],
            ag_deadline=dl))
        if nranks >= 2:
            if choice == "rs_ag":
                prog_nodes.extend(ir.zero_pair(f"b{i}", nranks, order,
                                               ag_deadline=dl))
            elif choice == "rs_resident":
                # Shard residency: the owner shard stays resident on
                # the optimizer path and the next forward reads it in
                # place — the allgather node is elided entirely.
                rs, _ag = ir.zero_pair(f"b{i}", nranks, order,
                                       ag_deadline=dl)
                prog_nodes.append(rs)
                SPC.record("sched_ag_elided_total")
            else:
                prog_nodes.append(ir.ProgramNode(
                    f"b{i}", ir.ring(nranks, order), ()))
    interleave = tuple(sorted(
        range(len(nodes)), key=lambda i: choices[i]["interleave"]))
    meta = {
        "seed": seed,
        "topo": (topo_fp or "none")[:16],
        "choices": ",".join(f"b{i}:{n.choice}"
                            for i, n in enumerate(nodes)),
        "tiles": ",".join(f"b{i}:{n.tiles}x{n.tile_elems}"
                          for i, n in enumerate(nodes)),
        "sources": ",".join(f"b{i}:{n.tile_source}"
                            for i, n in enumerate(nodes)),
        "interleave": ",".join(str(i) for i in interleave),
    }
    if any(n.ag_deadline is not None for n in nodes):
        # Deadlines are compile inputs that changed what executes —
        # they join the digest; absent entirely (the pre-slipstream
        # shape) the meta and digest stay byte-stable.
        meta["deadlines"] = ",".join(
            f"b{i}:{'-' if n.ag_deadline is None else n.ag_deadline}"
            for i, n in enumerate(nodes))
    program = ir.Program(name=name, nranks=nranks,
                         nodes=tuple(prog_nodes), meta=meta)
    ir.check_program(program)
    # Fuse dense round-uniform node groups per op into single Pallas
    # table programs (reduce_scatter keeps per-node kernels — its
    # output contract is one chunk per rank).
    fused: dict[str, ir.Schedule] = {}
    if nranks >= 2:
        for op in ("allreduce", "allgather"):
            group = [nd.schedule for nd in program.nodes
                     if nd.schedule.op == op]
            if len(group) >= 2:
                fused[op] = _pallas.fuse_schedules(
                    f"{name}.fused_{op}", group)
    SPC.record("sched_program_compiles_total")
    return CompiledStep(
        program=program, nodes=tuple(nodes), interleave=interleave,
        fused=fused, nranks=nranks, seed=seed, topo_fp=topo_fp,
        compile_ms=(time.perf_counter() - t0) * 1e3)


class ShardedAllreduce:
    """ZeRO-style execution of one bucket: its tile span splits into
    per-shard :class:`~ompi_tpu.coll.partitioned.PartitionedAllreduce`
    flows, each rooted at its shard OWNER — the reduce-scatter half of
    the node pair is the gather-to-owner, the allgather half the
    owner's slice of the merged broadcast. Shard boundaries are
    tile-aligned (shard s owns ``block_range(s, nshards, tiles)``), and
    every shard pins the bucket's uniform ``tile_elems`` so bucket tile
    t maps to exactly one shard-local tile.

    Duck-types the PartitionedAllreduce surface the overlap session
    drives (tiles/tile_elems/tile_range/ready_range/start/wait/abort/
    reduced/poll/_pump/_active/t_first_ready/t_reduce_done).
    """

    def __init__(self, comm, elems: int, dtype, *, op: Any = "sum",
                 tiles: int = 8, tile_elems: Optional[int] = None,
                 tag_base: int = 900, label: str = "",
                 defer_bcast: bool = False,
                 auto_pump: bool = True) -> None:
        from ..partitioned import PartitionedAllreduce

        self._comm = comm
        self._elems = int(elems)
        self._dtype = np.dtype(str(np.dtype(dtype)))
        self.tiles = max(1, min(int(tiles), self._elems))
        et = (int(tile_elems) if tile_elems
              else -(-self._elems // self.tiles))
        self.tile_elems = max(1, min(et, self._elems))
        self.tiles = -(-self._elems // self.tile_elems)
        self.label = label or "rsag"
        self.quant_wire = False  # shard flows always ride the exact wire
        self.nshards = min(comm.size, self.tiles)
        self._shards: list = []
        for s in range(self.nshards):
            t_lo, t_hi = block_range(s, self.nshards, self.tiles)
            e_lo = t_lo * self.tile_elems
            e_hi = min(t_hi * self.tile_elems, self._elems)
            pa = PartitionedAllreduce(
                comm, np.zeros((comm.size, e_hi - e_lo), self._dtype),
                op=op, tiles=t_hi - t_lo, tag=tag_base + s, root=s,
                allow_quant=False, label=f"{self.label}.s{s}",
                tile_elems=self.tile_elems, defer_bcast=defer_bcast,
                auto_pump=auto_pump)
            self._shards.append((t_lo, t_hi, e_lo, e_hi, pa))

    # -- PartitionedAllreduce-compatible surface -----------------------

    @property
    def _active(self) -> bool:
        return any(pa._active for *_, pa in self._shards)

    @property
    def reduced(self) -> bool:
        return all(pa.reduced for *_, pa in self._shards)

    @property
    def t_first_ready(self):
        ts = [pa.t_first_ready for *_, pa in self._shards
              if pa.t_first_ready is not None]
        return min(ts) if ts else None

    @property
    def t_reduce_done(self):
        ts = [pa.t_reduce_done for *_, pa in self._shards]
        return None if any(t is None for t in ts) else max(ts)

    def start(self) -> "ShardedAllreduce":
        for *_, pa in self._shards:
            pa.start()
        return self

    def tile_range(self, t: int) -> tuple:
        if not 0 <= t < self.tiles:
            raise ArgumentError(f"tile {t} out of range [0, {self.tiles})")
        lo = t * self.tile_elems
        return lo, min(lo + self.tile_elems, self._elems)

    def ready(self, t: int, data) -> None:
        self.ready_range(t, t, data)

    def ready_range(self, lo: int, hi: int, data) -> None:
        """Split a bucket-tile range across the shard flows; each shard
        sees shard-local tile indices and its slab slice."""
        if hi < lo:
            raise ArgumentError(f"ready_range: hi {hi} < lo {lo}")
        host = np.asarray(data)
        base = lo * self.tile_elems
        for t_lo, t_hi, e_lo, e_hi, pa in self._shards:
            s_lo, s_hi = max(lo, t_lo), min(hi, t_hi - 1)
            if s_hi < s_lo:
                continue
            col_lo = s_lo * self.tile_elems - base
            col_hi = min((s_hi + 1) * self.tile_elems, self._elems) - base
            pa.ready_range(s_lo - t_lo, s_hi - t_lo,
                           host[:, col_lo:col_hi])

    def _pump(self) -> int:
        return sum(pa._pump() for *_, pa in self._shards)

    def poll(self) -> bool:
        if not self.reduced:
            _progress.ENGINE.progress_until(
                lambda: self.reduced, timeout=0.0)
        return self.reduced

    def wait(self, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        parts = []
        for *_, pa in self._shards:
            parts.append(pa.wait(max(0.1, deadline - time.monotonic())))
        if any(p is None for p in parts):
            return None  # defer_bcast: executor assembles the step
        return np.concatenate([np.asarray(p) for p in parts], axis=1)

    def abort(self) -> None:
        for *_, pa in self._shards:
            pa.abort()

    @property
    def tail_armed(self) -> bool:
        """Every shard's deferred broadcast tail is armed (slipstream's
        schedulable-tail-node readiness: see PartitionedAllreduce
        .tail_armed)."""
        return all(pa.tail_armed for *_, pa in self._shards)

    def local_segments(self) -> list:
        """(root, col_lo, col_hi, local_1d) per shard — the merged
        broadcast's input slices (defer_bcast mode)."""
        return [(pa._root, e_lo, e_hi, pa.local_reduced())
                for _, _, e_lo, e_hi, pa in self._shards]


class StepExecutor:
    """Live-transport binding of one :class:`CompiledStep`.

    Owns the per-bucket collective flows (so ``parallel/`` never
    constructs them in a loop again), arms them in the compiled
    interleave order inside ONE dispatch window, drains arrivals
    through ONE merged progress callback, and — in step-program mode —
    finishes with ONE merged broadcast per distinct root (typically a
    single collective for the whole step) instead of one per bucket.

    ``legacy=True`` reproduces the PR 15 per-bucket behaviour exactly
    (per-bucket broadcast fired from the drain, one engine callback per
    bucket) — the bench's comparison arm.
    """

    def __init__(self, comm, compiled: CompiledStep, *,
                 op: Any = "sum", allow_quant: Optional[bool] = None,
                 tag_base: int = 820, legacy: bool = False) -> None:
        from ..partitioned import PartitionedAllreduce

        if compiled.nranks != comm.size:
            raise ArgumentError(
                f"step program compiled for {compiled.nranks} ranks, "
                f"comm has {comm.size}")
        self._comm = comm
        self.compiled = compiled
        self._legacy = bool(legacy)
        self._pump_on = False
        self.bindings: list = []
        tag = tag_base
        for nd in compiled.nodes:
            if (nd.choice in ("rs_ag", "rs_resident")
                    and comm.size >= 2):
                b = ShardedAllreduce(
                    comm, nd.elems, nd.dtype, op=op, tiles=nd.tiles,
                    tile_elems=nd.tile_elems, tag_base=tag,
                    label=nd.name, defer_bcast=not legacy,
                    auto_pump=legacy)
                tag += b.nshards
            else:
                b = PartitionedAllreduce(
                    comm, np.zeros((comm.size, nd.elems), nd.dtype),
                    op=op, tiles=nd.tiles, tag=tag,
                    allow_quant=allow_quant, label=nd.name,
                    tile_elems=nd.tile_elems, defer_bcast=not legacy,
                    auto_pump=legacy)
                tag += 1
            self.bindings.append(b)

    def begin_step(self) -> "StepExecutor":
        """Arm every node's persistent flow in the compiled interleave
        order, inside one dispatch window; register the merged drain."""
        from ..partitioned import _batch_window

        with _batch_window():
            for i in self.compiled.interleave:
                self.bindings[i].start()
        if not self._legacy:
            _progress.register(self._pump)
            self._pump_on = True
        return self

    def _pump(self) -> int:
        """The step's single merged progress callback: one drain sweep
        over every node flow."""
        return sum(b._pump() for b in self.bindings)

    def wait_all(self, timeout: float = 60.0) -> list:
        """Wait every node's reduction, then resolve results: legacy
        mode returns each bucket's own broadcast result; step-program
        mode fires the merged per-root broadcast and reassembles.
        Equivalent to ``wait_reduced()`` + ``finish_tail()`` — the
        slipstream window session calls the halves separately so the
        tail can dispatch under the next step's backward."""
        got = self.wait_reduced(timeout)
        if self._legacy:
            return got
        return self.finish_tail()

    def wait_reduced(self, timeout: float = 60.0):
        """Drive every node's reduction to completion WITHOUT firing
        the merged broadcast tail. Legacy mode (no deferred tail)
        returns the per-bucket results; step-program mode returns None
        with every binding's tail armed and the merged drain dropped
        (nothing left to pump — the tail is a plain collective)."""
        deadline = time.monotonic() + timeout
        raw = []
        for b in self.bindings:
            raw.append(b.wait(max(0.1, deadline - time.monotonic())))
        if self._legacy:
            return [np.asarray(r) for r in raw]
        self._drop_pump()
        return None

    def finish_tail(self) -> list:
        """Fire the merged per-root broadcast tail and reassemble the
        step's outputs. Requires every binding's tail armed (i.e. a
        completed ``wait_reduced``)."""
        for i, b in enumerate(self.bindings):
            if not b.tail_armed:
                raise RequestError(
                    f"finish_tail: node {self.compiled.nodes[i].name} "
                    f"tail not armed — wait_reduced() first")
        try:
            return self._merged_bcast()
        finally:
            self._drop_pump()

    def _merged_bcast(self) -> list:
        """ONE broadcast per distinct root for the whole step: every
        deferred root-local segment concatenates (as raw bytes, so
        mixed-dtype buckets share the collective) into a single
        rank-major buffer, and the replicated result splits back into
        per-bucket (size, elems) views.

        rs_resident buckets never enter the broadcast: their owner
        shards stay resident and every rank's "next-forward read" is
        assembled directly from the resident owner segment — the
        elided allgather is exactly this skipped wire traffic."""
        import jax.numpy as jnp

        size = self._comm.size
        segs: list = []  # (root, bucket, col_lo, col_hi, bytes_1d)
        out = [np.zeros((size, nd.elems), nd.dtype)
               for nd in self.compiled.nodes]
        for i, b in enumerate(self.bindings):
            if self.compiled.nodes[i].choice == "rs_resident":
                for root, lo, hi, local in b.local_segments():
                    out[i][:, lo:hi] = np.asarray(local)[None, :]
                continue
            if isinstance(b, ShardedAllreduce):
                for root, lo, hi, local in b.local_segments():
                    segs.append((root, i, lo, hi,
                                 np.ascontiguousarray(local)
                                 .view(np.uint8)))
            else:
                segs.append((b._root, i, 0, b._elems,
                             np.ascontiguousarray(b.local_reduced())
                             .view(np.uint8)))
        by_root: dict[int, list] = {}
        for seg in segs:
            by_root.setdefault(seg[0], []).append(seg)
        for root in sorted(by_root):
            group = sorted(by_root[root], key=lambda s: (s[1], s[2]))
            blob = np.concatenate([s[4] for s in group])
            stacked = np.zeros((size, blob.size), np.uint8)
            stacked[root] = blob
            rep = np.asarray(self._comm.bcast(jnp.asarray(stacked),
                                              root))
            row, off = rep[root], 0
            for _, i, lo, hi, raw in group:
                nd = self.compiled.nodes[i]
                out[i][:, lo:hi] = row[off:off + raw.size].view(nd.dtype)
                off += raw.size
        return out

    def abort(self) -> None:
        """Abandon the open step: drop the merged drain and abort every
        node flow (DESIGN.md §20 abandoned-tile hazards apply)."""
        self._drop_pump()
        for b in self.bindings:
            b.abort()

    def _drop_pump(self) -> None:
        if self._pump_on:
            _progress.unregister(self._pump)
            self._pump_on = False


__all__ = ["CompiledStep", "NodePlan", "ShardedAllreduce",
           "StepExecutor", "compile_step"]

"""Cold-start priors: the static algorithm tables.

This is where the reference's fixed decision rules
(coll_tuned_decision_fixed.c) now live — demoted from *the* decision
to the cold-start prior consulted only when the compiled-schedule
cache has no tuned winner for the (op, size-bucket, dtype, nranks,
topology) key. The byte thresholds themselves stay on the coll_tuned
cvar surface (tuned.py registers them; users override them the same
way as before) — this module owns the *logic* that turns thresholds
into picks, and the commlint ``schedcutoff`` rule keeps new hard-coded
byte cutoffs from growing anywhere in coll/ except here.

Every ``nbytes`` parameter below is BYTES PER RANK (the block size of
the rank-major payload, tuned._nbytes) — the single byte convention
shared with Rules bands and sched/cache size buckets.
"""

from __future__ import annotations

from typing import Optional

from ...ops import Op
from ...ops.op import _is_joint


def _t():
    # tuned registers the cvars and imports this module lazily from its
    # decide_* bodies, so by first call the module object exists.
    from .. import tuned

    return tuned


def prior_allreduce(op: Op, nbytes: int, nranks: int, dtype=None,
                    allow_quant: Optional[bool] = None,
                    rules=None) -> str:
    """Reference regime: recursive doubling < 10 KB/rank, ring to
    1 MiB/rank, segmented ring above — with the TPU-first native
    preference and the quantized-wire gate ahead of both."""
    t = _t()
    from .. import quant

    # Quantized wire: before native — trading representable values for
    # wire bytes only pays on the wire-bound (large, floating, SUM)
    # band, and only when the user (cvar/caller) and rules all agree.
    if allow_quant is None:
        allow_quant = quant._enable_var.value
    if (allow_quant
            and nbytes >= quant._min_bytes_var.value
            and quant.supports(op, dtype)
            and (rules is None
                 or rules.allows_quant("allreduce", nbytes, nranks,
                                       dtype))):
        return "quant_ring"
    if t._prefer_native.value and op.xla_reduce is not None:
        return "native"
    if nbytes < t._small.value:
        return "recursive_doubling"
    if nbytes <= t._ring_limit.value:
        return "ring"
    return "ring_segmented"


def prior_alltoall(nbytes_per_dest: int, nranks: int) -> str:
    t = _t()
    if nbytes_per_dest <= t._alltoall_small.value and nranks >= 8:
        return "bruck"
    if nbytes_per_dest >= t._alltoall_large.value:
        return "pairwise"
    return "native"


def prior_allgather(nbytes: int, nranks: int) -> str:
    return "native"


def prior_bcast(nbytes: int, nranks: int) -> str:
    """Reference regime (coll_tuned_decision_fixed.c:250-310): binomial
    small, binary tree mid-size, segmented pipeline for bulk; native
    wins when preferred — XLA already emits the ICI-optimal schedule."""
    t = _t()
    if t._prefer_native.value:
        return "native"
    if nbytes < t._small.value:
        return "binomial"
    if nbytes < t._large.value:
        return "binary"
    return "pipelined"


def prior_scan(op: Op, nbytes: int, nranks: int) -> str:
    t = _t()
    if _is_joint(op):
        return "native"
    if t._prefer_native.value:
        return "native"
    if nbytes < t._small.value:
        return "recursive_doubling"
    return "native"


def prior_exscan(op: Op, nbytes: int, nranks: int) -> str:
    return prior_scan(op, nbytes, nranks)


def prior_reduce(op: Op, nbytes: int, nranks: int) -> str:
    """Reference: binomial small, pipelined chains above; the ordered
    native path for non-commutative ops."""
    t = _t()
    if not op.commutative or _is_joint(op):
        return "native"  # ordered handling lives in the algo fallback
    if t._prefer_native.value and op.xla_reduce is not None:
        return "native"
    if nbytes < t._small.value:
        return "binomial"
    if nbytes >= t._large.value:
        return "pipelined"  # segmented chain (reference pipeline tier)
    return "native"


def prior_reduce_scatter(op: Op, nbytes: int, nranks: int) -> str:
    """Reference: coll_base_reduce_scatter.c — recursive halving for
    small commutative power-of-two cases, ring for large."""
    t = _t()
    if not op.commutative or _is_joint(op):
        # ring/halving accumulate out of rank order; the native path's
        # ordered gather-reduce fallback is the only correct one
        return "native"
    if t._prefer_native.value and op.xla_reduce is not None:
        return "native"
    pof2 = nranks & (nranks - 1) == 0
    if op.commutative and pof2 and nbytes < t._small.value:
        return "recursive_halving"
    return "ring"


def prior_gather(nbytes: int, nranks: int) -> str:
    t = _t()
    if nbytes < t._gather_binomial_max.value and nranks >= 4:
        return "binomial"
    return "native"


def prior_scatter(nbytes: int, nranks: int) -> str:
    # Always native: on a single controller scatter is a pure reshard;
    # the tree forms are reachable only by forced var or rules file.
    return "native"


__all__ = [
    "prior_allgather", "prior_allreduce", "prior_alltoall",
    "prior_bcast", "prior_exscan", "prior_gather", "prior_reduce",
    "prior_reduce_scatter", "prior_scan", "prior_scatter",
]

"""SLO-aware schedule selection: latency targets over the frontier.

The autotuner's winner is the pure-throughput point — minimum modeled
cost. An SLO flips the objective: given a per-communicator p50 target,
``decide_*`` should pick the *cheapest-wire* point on the cached
latency/bandwidth frontier that still meets the target (don't spend
fabric bytes on latency headroom nobody asked for), falling back to
the throughput winner when no point meets it (the watchtower then
accounts the violation minutes per tenant scope).

Frontier semantics: retune/autotune store per-candidate
``{"algo", "score", "steps", "wire"}`` points on the cache entry
(non-semantic: excluded from the digest). Estimated p50 for a point is
score-proportional off the entry's live-measured baseline::

    est_p50_us(c) = baseline_p50_us * score(c) / score(winner)

so the estimate self-calibrates to the machine the baseline was
measured on. With no baseline stamped yet there is no absolute
latency scale and the winner stands — SLO selection is advisory
until the watchtower has observed the key once.

Targets: the ``coll_slo_p50_us`` cvar is the fleet-wide default
(0 = off); ``set_target(scope, us)`` overrides per communicator
scope (the health ledger's scope convention, ``str(comm.cid)``).
"""

from __future__ import annotations

import threading
from typing import Optional

from ...core import config
from ...core.counters import SPC
from ...core.logging import get_logger

logger = get_logger("coll.sched")

_target_var = config.register(
    "coll", "slo", "p50_us", type=float, default=0.0,
    description="Fleet-wide allreduce p50 SLO target in microseconds "
                "(0 = off): decide_* picks the cheapest-wire frontier "
                "point meeting it instead of the pure-throughput "
                "winner; per-communicator overrides via "
                "slo.set_target(scope, us)",
)

_mu = threading.Lock()
_targets: dict[str, float] = {}
_violation_s: dict[str, float] = {}
_gen = 0


def set_target(scope: str, p50_us: Optional[float]) -> None:
    """Per-scope SLO override (None/0 clears it). Bumps the module
    generation so memoized dispatch plans re-consult."""
    global _gen
    with _mu:
        if not p50_us:
            _targets.pop(str(scope), None)
        else:
            _targets[str(scope)] = float(p50_us)
        _gen += 1


def generation() -> int:
    """Target-change counter (tuned._fast_allreduce stamps it; the
    global cvar rides config.generation() instead)."""
    return _gen


def target_for(scope: Optional[str] = None) -> float:
    """The effective p50 target (µs) for a scope; 0 = no SLO."""
    if scope is not None:
        with _mu:
            t = _targets.get(str(scope))
        if t:
            return t
    return float(_target_var.value or 0.0)


def targets() -> dict[str, float]:
    """Every scope with an explicit target (the watchtower's
    violation-accounting worklist; the global cvar rides scope
    ``"world"`` when set)."""
    with _mu:
        out = dict(_targets)
    g = float(_target_var.value or 0.0)
    if g and "world" not in out:
        out["world"] = g
    return out


def frontier_pick(entry: dict, target_us: float) -> Optional[str]:
    """The SLO point on an entry's frontier: among candidates whose
    estimated p50 meets ``target_us``, the one with the least wire
    bytes. None when the frontier/baseline is missing or when not even
    the winner meets the target (the caller keeps the winner and the
    violation is accounted, not hidden by a worse pick)."""
    frontier = entry.get("frontier")
    baseline = entry.get("baseline_p50_us")
    if not frontier or not baseline or target_us <= 0:
        return None
    best_score = min(c["score"] for c in frontier)
    if best_score <= 0:
        return None
    feasible = [c for c in frontier
                if baseline * c["score"] / best_score <= target_us]
    if not feasible:
        return None
    return min(feasible, key=lambda c: (c["wire"], c["score"]))["algo"]


def note_violation(scope: str, seconds: float) -> None:
    """Accumulate SLO-violation wall time for a tenant scope (the
    watchtower calls this per tick the live p50 misses the target)."""
    with _mu:
        _violation_s[str(scope)] = (_violation_s.get(str(scope), 0.0)
                                    + float(seconds))
    SPC.record("sched_slo_violation_ticks")


def violation_minutes() -> dict[str, float]:
    """Per-scope violation minutes (the Prometheus export shape)."""
    with _mu:
        return {s: round(v / 60.0, 6) for s, v in _violation_s.items()}


def reset_for_testing() -> None:
    global _gen
    with _mu:
        _targets.clear()
        _violation_s.clear()
        _gen += 1


__all__ = [
    "frontier_pick", "generation", "note_violation",
    "reset_for_testing", "set_target", "target_for", "targets",
    "violation_minutes",
]

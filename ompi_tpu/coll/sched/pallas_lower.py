"""Pallas lowering backend: Schedule IR -> one fused TPU kernel.

Where ``lower.py``'s interpret mode turns each IR round into a
``lax.ppermute``, this backend compiles the whole step program into a
single ``pltpu.make_async_remote_copy`` kernel: every round is one
remote DMA overlapped with the combine on the VPU, flowing through the
two-slot comm-buffer credit discipline proven in
``coll/pallas_ring.py``'s hand-written ring kernels — but generated
from the IR, so topology ring orders, segment counts and future step
programs ride the same codegen.

Supported programs — the "dense chained round-uniform" contract:

- **dense**: every rank sends exactly once and receives exactly once
  in every round (ring, segmented ring, the reduce-scatter phase;
  *not* hierarchical, whose member ranks idle during the leader
  chain);
- **chained or fresh**: for each round r >= 1 either every rank sends
  the chunk it received in round r-1 (the value is already in the comm
  buffer — the ring chain), or every rank sends a chunk it has never
  received (a segment boundary: re-stage from the input). Mixed rounds
  are rejected;
- **round-uniform**: the receive kind (reduce/copy) and the
  is-last-receive-of-chunk property must not vary across ranks within
  a round, so they unroll to Python constants in the kernel.

The kernel is rank-generic: the per-round peer/chunk assignments are
passed as four (rounds, nranks) int32 tables in SMEM and indexed by
``lax.axis_index`` at trace time, so one compiled kernel serves every
rank exactly like the hand-written ones.

Slot math (the double-buffer invariant): round r reads comm_buf[r%2]
and lands the incoming chunk in comm_buf[(r+1)%2]. The slot a round
drains is refilled two rounds later, and that refill is gated by the
drain credit (cap_sem) signalled to the *round r+2 sender* — which the
tables name explicitly, where the hand kernels could hardcode "left".
Global slot parity means segment boundaries need no extra barrier: the
re-staged slot's previous arrival was drained locally one round
earlier, and the next remote write into it is still credit-gated.

Validation: ``lower.validate_schedule`` runs these kernels under
Mosaic's TPU interpret mode on CPU (the mode that emulates remote DMA
+ semaphore signals) and byte-compares against the ring reference —
tier-1 covers the codegen path without hardware. On jax builds that
ship the DMA primitives but not the CPU emulation (0.4.x; see
``pallas_ring.interpret_available``), ``simulate`` is the oracle: it
executes the same table program with the kernel's exact slot/store
semantics and the kernel itself is checked by abstract tracing.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Callable

import numpy as np

from ...core.errors import ArgumentError
from .ir import ANNOTATIONS, Schedule, Step, check as _check

#: collective_id namespace: 0-11 belong to the hand-written coll
#: kernels (pallas_ring, pallas_shift, quant, ...); the sched compiler
#: owns 12 (allreduce programs), 13 (reduce-scatter programs),
#: 14 (allgather programs — the AG half of a ZeRO-style step node) and
#: 15 (step-boundary window programs: a step's allgather tail fused
#: with the next step's first reduce-scatter group — slipstream).
_COLLECTIVE_ID = {"allreduce": 12, "reduce_scatter": 13, "allgather": 14,
                  "window": 15}

#: compiled-wrapper memo keyed by schedule digest (kernel analysis is
#: pure python; jit caching happens downstream in compile_plan).
_COMPILED: dict[str, Callable] = {}


@dataclass(frozen=True)
class _Program:
    """Kernel-ready constants extracted from a Schedule.

    The tables are (rounds, nranks) int32; ``mode``/``last``/``brk``
    are per-round Python constants (round-uniformity is what makes the
    unrolled kernel rank-generic)."""

    op: str
    nranks: int
    nchunks: int
    rounds: int
    mode: tuple       # 1=reduce, 2=copy
    last: tuple       # this round's value is the chunk's final value
    brk: tuple        # chain-break round: re-stage send chunk from x
    t_dst: np.ndarray    # [r, k] -> peer k sends to
    t_src: np.ndarray    # [r, k] -> peer that sends to k
    t_schunk: np.ndarray  # [r, k] -> chunk k sends
    t_rchunk: np.ndarray  # [r, k] -> chunk k receives into


def analyze(sched: Schedule) -> _Program:
    """Check the dense/chained/round-uniform contract and extract the
    kernel tables. Raises ArgumentError with the violated clause."""
    n, rounds = sched.nranks, sched.rounds()
    if sched.op not in _COLLECTIVE_ID:
        raise ArgumentError(
            f"pallas lowering supports ops {sorted(_COLLECTIVE_ID)}, "
            f"schedule {sched.name!r} is op={sched.op!r}")
    if any(s.kind in ANNOTATIONS for s in sched.steps):
        raise ArgumentError(
            f"schedule {sched.name!r} carries quant/dequant annotations"
            f" — quantized wires keep the primitive lowering")
    if rounds < 1:
        raise ArgumentError(f"schedule {sched.name!r} has no rounds")
    sends: list[dict] = [{} for _ in range(rounds)]
    recvs: list[dict] = [{} for _ in range(rounds)]
    for s in sched.steps:
        (sends if s.kind == "send" else recvs)[s.round][s.rank] = s
    t_dst = np.zeros((rounds, n), np.int32)
    t_src = np.zeros((rounds, n), np.int32)
    t_schunk = np.zeros((rounds, n), np.int32)
    t_rchunk = np.zeros((rounds, n), np.int32)
    mode, last, brk = [], [], []
    seen: list[set] = [set() for _ in range(n)]  # chunks k received
    for r in range(rounds):
        if set(sends[r]) != set(range(n)) or set(recvs[r]) != set(range(n)):
            raise ArgumentError(
                f"schedule {sched.name!r} round {r} is not dense: every"
                f" rank must send once and receive once (hierarchical-"
                f"style idle ranks have no pallas lowering)")
        kinds = {recvs[r][k].kind for k in range(n)}
        if len(kinds) != 1:
            raise ArgumentError(
                f"schedule {sched.name!r} round {r} mixes receive kinds"
                f" {sorted(kinds)} across ranks")
        mode.append(1 if kinds.pop() == "reduce" else 2)
        for k in range(n):
            t_dst[r, k] = sends[r][k].peer
            t_src[r, k] = recvs[r][k].peer
            t_schunk[r, k] = sends[r][k].chunk
            t_rchunk[r, k] = recvs[r][k].chunk
        if r == 0:
            brk.append(True)  # round 0 always stages from the input
        else:
            chained = all(t_schunk[r, k] == t_rchunk[r - 1, k]
                          for k in range(n))
            fresh = all(t_schunk[r, k] not in seen[k] for k in range(n))
            if not chained and not fresh:
                raise ArgumentError(
                    f"schedule {sched.name!r} round {r} is neither "
                    f"chained (send what round {r - 1} received) nor a "
                    f"uniform re-stage of untouched chunks")
            brk.append(not chained)
        if mode[r] == 1:
            for k in range(n):
                if t_rchunk[r, k] in seen[k]:
                    raise ArgumentError(
                        f"schedule {sched.name!r} round {r}: rank {k} "
                        f"reduces into chunk {t_rchunk[r, k]} it already"
                        f" received — the kernel combines against the "
                        f"original input")
        for k in range(n):
            seen[k].add(int(t_rchunk[r, k]))
    for r in range(rounds):
        flags = {t_rchunk[r, k] not in
                 {int(t_rchunk[q, k]) for q in range(r + 1, rounds)}
                 for k in range(n)}
        if len(flags) != 1:
            raise ArgumentError(
                f"schedule {sched.name!r} round {r}: is-last-receive "
                f"varies across ranks")
        last.append(flags.pop())
    if sched.op == "allreduce":
        for k in range(n):
            if seen[k] != set(range(sched.nchunks)):
                raise ArgumentError(
                    f"schedule {sched.name!r}: rank {k} never receives "
                    f"chunks {sorted(set(range(sched.nchunks)) - seen[k])}"
                    f" — the output would be partial")
    if sched.op == "allgather":
        # A rank never receives its own chunk: it reaches the output at
        # the stage/re-stage rounds instead, so completeness is
        # received ∪ staged.
        for k in range(n):
            own = {int(t_schunk[r, k]) for r in range(rounds) if brk[r]}
            missing = set(range(sched.nchunks)) - (seen[k] | own)
            if missing:
                raise ArgumentError(
                    f"schedule {sched.name!r}: rank {k} neither receives"
                    f" nor stages chunks {sorted(missing)} — the output "
                    f"would be partial")
    if sched.op == "window":
        # Boundary-spanning programs (slipstream): completeness holds
        # member-wise. Segments are the brk-delimited round runs; each
        # must be mode-uniform — an allgather tail member is all-copy,
        # a reduce-scatter member all-reduce — and copy segments must
        # cover their chunk universe like a standalone allgather.
        seg_start = [r for r in range(rounds) if brk[r]]
        for si, s0 in enumerate(seg_start):
            s1 = (seg_start[si + 1] if si + 1 < len(seg_start)
                  else rounds)
            modes = {mode[r] for r in range(s0, s1)}
            if len(modes) != 1:
                raise ArgumentError(
                    f"schedule {sched.name!r}: window segment rounds "
                    f"{s0}..{s1 - 1} mix reduce and copy receive kinds")
            if modes == {2}:
                universe = {int(t_schunk[r, k])
                            for r in range(s0, s1) for k in range(n)}
                universe |= {int(t_rchunk[r, k])
                             for r in range(s0, s1) for k in range(n)}
                for k in range(n):
                    got = {int(t_rchunk[r, k]) for r in range(s0, s1)}
                    got |= {int(t_schunk[r, k]) for r in range(s0, s1)
                            if brk[r]}
                    missing = universe - got
                    if missing:
                        raise ArgumentError(
                            f"schedule {sched.name!r}: window copy "
                            f"segment at round {s0}: rank {k} neither "
                            f"receives nor stages chunks "
                            f"{sorted(missing)}")
    return _Program(op=sched.op, nranks=n, nchunks=sched.nchunks,
                    rounds=rounds, mode=tuple(mode), last=tuple(last),
                    brk=tuple(brk), t_dst=t_dst, t_src=t_src,
                    t_schunk=t_schunk, t_rchunk=t_rchunk)


def fuse_schedules(name: str, scheds) -> Schedule:
    """Chain same-op, same-rank-count dense schedules into ONE table
    program: member i's chunks occupy the id range ``[base_i, base_i +
    nchunks_i)`` and its rounds follow member i-1's. The first round of
    each member is a segment boundary — every rank re-stages a chunk it
    has never received, exactly ``segmented_ring``'s structure, which
    ``analyze`` already accepts as a chain-break re-stage — so a whole
    step program's worth of ring collectives compiles to a single
    fused kernel instead of one per bucket.

    Reduce-scatter members are rejected: the RS kernel's output
    contract is one chunk per rank, which a multi-segment table would
    silently violate.
    """
    scheds = list(scheds)
    if not scheds:
        raise ArgumentError("fuse_schedules needs at least one schedule")
    op, n = scheds[0].op, scheds[0].nranks
    if op == "reduce_scatter":
        raise ArgumentError(
            "fuse_schedules: reduce_scatter programs keep per-node "
            "kernels (single-chunk output contract)")
    for s in scheds:
        if s.op != op or s.nranks != n:
            raise ArgumentError(
                f"fuse_schedules: member {s.name!r} is "
                f"(op={s.op!r}, nranks={s.nranks}), group is "
                f"(op={op!r}, nranks={n})")
    steps: list[Step] = []
    chunk_base = round_base = 0
    for s in scheds:
        for st in s.steps:
            steps.append(Step(st.round + round_base, st.kind, st.rank,
                              st.peer, st.chunk + chunk_base))
        chunk_base += s.nchunks
        round_base += s.rounds()
    fused = Schedule(
        name=name, op=op, nranks=n, nchunks=chunk_base,
        steps=tuple(steps),
        meta={"tier": "device_pallas", "lowering": "pallas",
              "segments": len(scheds)},
    )
    _check(fused)
    analyze(fused)  # enforce the dense/chained/round-uniform contract
    return fused


def fuse_window(name: str, tail_scheds, next_scheds) -> Schedule:
    """Fuse a step-boundary window into ONE table program (slipstream):
    step N's merged broadcast tail — its dense round-uniform allgather
    members — chained with step N+1's first reduce-scatter group. Same
    chunk-base/round-base chaining as ``fuse_schedules``; each member
    start is a chain-break re-stage, which ``analyze`` already accepts.
    The fused op is ``"window"`` (collective_id 15): copy segments
    write like an allgather, reduce segments emit each rank's own
    reduced chunk at their segment-final round.

    The contract is strict — every tail member must be op="allgather",
    every next-step member op="reduce_scatter", all on one rank count —
    because a window that silently dropped a member would break the
    two-step bit-identity oracle. Callers treat ArgumentError as "keep
    per-node kernels for this boundary"."""
    tail = list(tail_scheds)
    nxt = list(next_scheds)
    if not tail or not nxt:
        raise ArgumentError(
            "fuse_window needs at least one tail member and one "
            "next-step member")
    n = tail[0].nranks
    for s in tail:
        if s.op != "allgather":
            raise ArgumentError(
                f"fuse_window: tail member {s.name!r} is op={s.op!r}, "
                f"the broadcast tail fuses allgather members only")
    for s in nxt:
        if s.op != "reduce_scatter":
            raise ArgumentError(
                f"fuse_window: next-step member {s.name!r} is "
                f"op={s.op!r}, the boundary fuses into the next step's "
                f"reduce-scatter group only")
    for s in tail + nxt:
        if s.nranks != n:
            raise ArgumentError(
                f"fuse_window: member {s.name!r} has nranks="
                f"{s.nranks}, window is nranks={n}")
    steps: list[Step] = []
    chunk_base = round_base = 0
    for s in tail + nxt:
        for st in s.steps:
            steps.append(Step(st.round + round_base, st.kind, st.rank,
                              st.peer, st.chunk + chunk_base))
        chunk_base += s.nchunks
        round_base += s.rounds()
    fused = Schedule(
        name=name, op="window", nranks=n, nchunks=chunk_base,
        steps=tuple(steps),
        meta={"tier": "device_pallas", "lowering": "pallas",
              "segments": len(tail) + len(nxt),
              "boundary": len(tail)},
    )
    _check(fused)
    analyze(fused)  # enforce the dense/chained/round-uniform contract
    return fused


def compile_schedule(sched: Schedule) -> Callable:
    """Schedule -> callable. Allreduce programs get the
    ALLREDUCE_ALGOS signature ``fn(x, axis_name, op)``; reduce-scatter
    programs the REDUCE_SCATTER_ALGOS one (``x`` is the local (n,
    chunk) contribution view, result the own reduced block)."""
    key = sched.digest()
    fn = _COMPILED.get(key)
    if fn is None:
        prog = analyze(sched)
        fn = _COMPILED[key] = _make_wrapper(prog, sched.name)
    return fn


def clear_compiled() -> None:
    """Forget compiled wrappers (tests / re-init)."""
    _COMPILED.clear()


def simulate(sched, data, op):
    """Host-side oracle: execute the extracted table program with the
    exact slot/store semantics of ``_kernel``, one rank at a time.

    ``data`` is the stacked per-rank input, shape (nranks, nchunks,
    chunk). Returns the stacked per-rank outputs: (nranks, nchunks,
    chunk) for allreduce, (nranks, chunk) for reduce_scatter.

    This is tier-1's bit-identity reference for the codegen when the
    installed jax has no Mosaic TPU interpret mode (0.4.x ships the
    remote-DMA primitives but not the CPU emulation of them): the
    simulator and the kernel share the table program, the two-slot
    comm-buffer discipline, the conditional combine store and the
    out-write gating, so a schedule whose simulation matches the
    mathematical reference exercises every decision ``analyze`` baked
    into the kernel. Uses jnp so bfloat16 rounds exactly as on device.
    """
    import jax.numpy as jnp

    from ...ops import lookup as op_lookup

    op = op_lookup(op)
    prog = analyze(sched) if isinstance(sched, Schedule) else sched
    n, rounds = prog.nranks, prog.rounds
    data = jnp.asarray(data)
    if data.ndim != 3 or data.shape[0] != n or data.shape[1] != prog.nchunks:
        raise ArgumentError(
            f"simulate expects data shaped ({n}, {prog.nchunks}, chunk),"
            f" got {data.shape}")
    comm: list[list] = [[None, None] for _ in range(n)]
    if prog.op == "reduce_scatter":
        out: list = [None] * n
    else:
        out = [[None] * prog.nchunks for _ in range(n)]
    for k in range(n):
        comm[k][0] = data[k, int(prog.t_schunk[0, k])]
    for r in range(rounds):
        slot, nslot = r % 2, (r + 1) % 2
        if r >= 1 and prog.brk[r]:
            for k in range(n):
                comm[k][slot] = data[k, int(prog.t_schunk[r, k])]
        if prog.brk[r] and (prog.op == "allgather"
                            or (prog.op == "window"
                                and prog.mode[r] == 2)):
            # Own chunk never travels: it reaches the output at the
            # stage round, mirroring the kernel's out-write. In a
            # window program this fires only for copy (allgather tail)
            # segments — a reduce-scatter member's stage round feeds
            # the wire, never the output.
            for k in range(n):
                c = int(prog.t_schunk[r, k])
                out[k][c] = data[k, c]
        # All round-r sends read their source slot before any round-r
        # arrival lands (the credit discipline guarantees this order on
        # device; here a snapshot does).
        arrivals = [comm[int(prog.t_src[r, k])][slot] for k in range(n)]
        for k in range(n):
            comm[k][nslot] = arrivals[k]
            if prog.mode[r] == 1:
                val = op.combine(comm[k][nslot],
                                 data[k, int(prog.t_rchunk[r, k])])
                if r + 1 < rounds and not prog.brk[r + 1]:
                    comm[k][nslot] = val
            else:
                val = comm[k][nslot]
            if prog.op == "reduce_scatter":
                if r == rounds - 1:
                    out[k] = val
            elif prog.op == "window" and prog.mode[r] == 1:
                # Reduce segment of a boundary window: only the
                # segment-final receive is fully reduced (the rank's
                # own shard) — intermediate receives are partial sums
                # forwarded down the chain, unlike an allreduce where
                # a chunk's last receive is final by construction.
                if r == rounds - 1 or prog.brk[r + 1]:
                    out[k][int(prog.t_rchunk[r, k])] = val
            elif prog.last[r]:
                out[k][int(prog.t_rchunk[r, k])] = val
    if prog.op == "reduce_scatter":
        return jnp.stack(out)
    if prog.op == "window":
        # Reduce-segment chunks a rank does not own never reach its
        # output — backfill with the rank's input so the stacked
        # result is dense (callers read only owned shards there).
        for k in range(n):
            for c in range(prog.nchunks):
                if out[k][c] is None:
                    out[k][c] = data[k, c]
    return jnp.stack([jnp.stack(row) for row in out])


def _kernel(axis_name: str, op, prog: _Program,
            t_dst, t_src, t_schunk, t_rchunk, x_ref, out_ref,
            comm_buf, send_sem, recv_sem, cap_sem):
    """The generated kernel body: the two-slot credit discipline of
    pallas_ring's ``_allreduce_kernel`` driven by the IR tables."""
    import jax
    from jax.experimental.pallas import tpu as pltpu

    me = jax.lax.axis_index(axis_name)
    rounds = prog.rounds
    comm_buf[0] = x_ref[t_schunk[0, me]]
    # Post-seed credit: gates the round-1 write into comm_buf[0] so a
    # fast upstream cannot land it before the seed (kernel-start skew;
    # no implicit entry barrier). A 1-round program has no round 1 —
    # the credit would leave cap_sem[0] non-zero at kernel exit.
    if rounds >= 2:
        pltpu.semaphore_signal(
            cap_sem.at[0], inc=1, device_id=t_src[1, me],
            device_id_type=pltpu.DeviceIdType.LOGICAL)
    for r in range(rounds):
        slot = r % 2
        nslot = (r + 1) % 2
        if r >= 1:
            # Backpressure: the downstream slot we are about to fill
            # was drained two rounds ago (round 1: the post-seed
            # credit).
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
            if prog.brk[r]:
                # Segment boundary: the chain restarts from a fresh
                # input chunk. Our slot's previous arrival was drained
                # at round r-1 and the next remote write into it (round
                # r+1) is still credit-gated, so a plain store is safe.
                comm_buf[slot] = x_ref[t_schunk[r, me]]
        if prog.brk[r] and (prog.op == "allgather"
                            or (prog.op == "window"
                                and prog.mode[r] == 2)):
            # A rank's own chunk never travels the ring: the staged
            # value IS its final value, written straight to the output
            # (copy segments only — a window's reduce-scatter member
            # stages for the wire, not the output).
            out_ref[t_schunk[r, me]] = x_ref[t_schunk[r, me]]
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=t_dst[r, me],
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if prog.mode[r] == 1:
            val = op.combine(comm_buf[nslot], x_ref[t_rchunk[r, me]])
            # The combined value only needs to persist in the comm
            # buffer when the next round forwards it down the chain.
            if r + 1 < rounds and not prog.brk[r + 1]:
                comm_buf[nslot] = val
        else:
            val = comm_buf[nslot]
        if prog.op == "reduce_scatter":
            if r == rounds - 1:
                out_ref[:] = val
        elif prog.op == "window" and prog.mode[r] == 1:
            # Reduce segment: only the segment-final receive is the
            # rank's fully-reduced own shard (see simulate).
            if r == rounds - 1 or prog.brk[r + 1]:
                out_ref[t_rchunk[r, me]] = val
        elif prog.last[r]:
            out_ref[t_rchunk[r, me]] = val
        # Drained comm_buf[nslot]; credit the rank that refills it at
        # round r+2.
        if r <= rounds - 3:
            pltpu.semaphore_signal(
                cap_sem.at[nslot], inc=1, device_id=t_src[r + 2, me],
                device_id_type=pltpu.DeviceIdType.LOGICAL)


def _pallas_call(prog: _Program, op, axis_name: str, state, chunk):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .. import pallas_ring

    if prog.op == "reduce_scatter":
        out_shape = jax.ShapeDtypeStruct((chunk,), state.dtype,
                                         vma=frozenset({axis_name}))
    else:
        out_shape = jax.ShapeDtypeStruct((prog.nchunks, chunk),
                                         state.dtype,
                                         vma=frozenset({axis_name}))
    kernel = functools.partial(_kernel, axis_name, op, prog)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 4
        + [pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk), state.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=_COLLECTIVE_ID[prog.op],
        ),
        interpret=pallas_ring._interpret(),
    )(prog.t_dst, prog.t_src, prog.t_schunk, prog.t_rchunk, state)


def _make_wrapper(prog: _Program, name: str) -> Callable:
    if prog.op == "reduce_scatter":
        def run_rs(x, axis_name: str, op):
            import jax
            import jax.numpy as jnp

            from ...ops import lookup as op_lookup

            op = op_lookup(op)
            n = jax.lax.axis_size(axis_name)
            if n != prog.nranks:
                raise ArgumentError(
                    f"schedule {name!r} compiled for {prog.nranks} "
                    f"ranks, axis {axis_name!r} has {n}")
            if x.shape[0] != n:
                raise ArgumentError(
                    f"reduce_scatter input leading dim {x.shape[0]} != "
                    f"ranks {n}")
            if n == 1:
                return x[0]
            shape = x.shape[1:]
            flat = x.reshape(n, -1)
            pad = (-flat.shape[1]) % 128
            if pad:
                flat = jnp.pad(flat, ((0, 0), (0, pad)))
            out = _pallas_call(prog, op, axis_name, flat, flat.shape[1])
            if pad:
                out = out[:-pad]
            return out.reshape(shape)

        return run_rs

    def run(x, axis_name: str, op):
        import jax
        import jax.numpy as jnp

        from ...ops import lookup as op_lookup

        op = op_lookup(op)
        n = jax.lax.axis_size(axis_name)
        if n != prog.nranks:
            raise ArgumentError(
                f"schedule {name!r} compiled for {prog.nranks} ranks, "
                f"axis {axis_name!r} has {n}")
        if n == 1:
            return x
        flat = x.reshape(-1)
        total = flat.shape[0]
        # The IR chunk plan sets the layout: nchunks equal slices, each
        # padded to the 128-lane tile quantum.
        chunk = -(-total // prog.nchunks)
        chunk = ((chunk + 127) // 128) * 128
        if chunk * prog.nchunks != total:
            flat = jnp.pad(flat, (0, chunk * prog.nchunks - total))
        out = _pallas_call(prog, op, axis_name,
                           flat.reshape(prog.nchunks, chunk), chunk)
        return out.reshape(-1)[:total].reshape(x.shape)

    return run


__all__ = ["analyze", "clear_compiled", "compile_schedule",
           "fuse_schedules", "fuse_window", "simulate"]

"""Online schedule retune: the sched half of the watchtower loop.

watchtower (telemetry/) decides *when* a cached winner has drifted;
this module decides *what to do about it* — deterministically. Two
mechanisms:

``retune_key``
    Re-run the model-mode candidate sweep for exactly one cache key
    and install the new winner through ``cache.bump()`` — a
    version-bumped entry that retains the old winner one level deep
    (``rollback()`` restores it). On drift retunes the incumbent
    algorithm is *excluded* from the sweep: the live measurement just
    falsified the model's prediction for it, so re-scoring it with the
    same model would deterministically re-elect it. The bump raises
    the cache generation, so memoized dispatch plans
    (``tuned._fast_allreduce``) re-consult at their next dispatch —
    a schedule is never mutated mid-flight.

topology penalties
    Persistent straggler findings reshape schedules instead of only
    marking tiers SUSPECT: ``set_topology_penalties`` records slow
    ranks and skew, and ``build_schedule`` consults
    ``reroot_groups``/``effective_segments``/``penalty_stamp`` so
    hierarchical trees re-root away from slow leaders and segmented
    rings shrink their chunks under skew. Penalties are inputs to the
    existing IR generators — the generated ``Schedule.digest()``
    stays a pure function of (algo, nranks, penalty state), keeping
    the byte-identity contract.

Determinism contract: every decision here is a pure function of the
cache key, the candidate pool, the seed, and the penalty state — no
wall-clock, no RNG beyond the model's seeded crc32 tie-break — so
same-seed controllers that observe the same drift install
byte-identical winners (the acceptance drill asserts this across two
subprocesses).
"""

from __future__ import annotations

import re
import threading
from typing import Optional, Sequence

from ...core.counters import SPC
from ...core.logging import get_logger
from . import cache as _cache

logger = get_logger("coll.sched")

#: ``cache_key`` grammar: op|b<bucket>|<dtype>|r<nranks>|<topo_fp>
_KEY_RE = re.compile(r"^([^|]+)\|b(\d+)\|([^|]+)\|r(\d+)\|(.*)$")


def parse_key(key: str) -> Optional[dict]:
    """Decompose a cache key back into its sweep coordinates (None for
    a key that doesn't match the grammar — e.g. a hand-edited file)."""
    m = _KEY_RE.match(key)
    if m is None:
        return None
    return {
        "opname": m.group(1),
        "bucket": int(m.group(2)),
        "dtype": m.group(3),
        "nranks": int(m.group(4)),
        "topo_fp": m.group(5),
    }


# ---------------------------------------------------------------------------
# topology penalties (straggler findings -> schedule shape)
# ---------------------------------------------------------------------------

_mu = threading.Lock()
_PENALTY = {"slow_ranks": frozenset(), "skew": False, "gen": 0}


def set_topology_penalties(slow_ranks: Sequence[int] = (),
                           skew: bool = False) -> bool:
    """Install the straggler-derived schedule penalties. Returns True
    when the state actually changed (the caller retunes only then)."""
    slow = frozenset(int(r) for r in slow_ranks)
    with _mu:
        if (_PENALTY["slow_ranks"] == slow
                and _PENALTY["skew"] == bool(skew)):
            return False
        _PENALTY["slow_ranks"] = slow
        _PENALTY["skew"] = bool(skew)
        _PENALTY["gen"] += 1
    from ...trace import span as tspan

    SPC.record("sched_topology_penalties")
    tspan.instant("sched.topology_penalty", cat="sched",
                  slow_ranks=sorted(slow), skew=bool(skew))
    logger.info("sched: topology penalties -> slow_ranks=%s skew=%s",
                sorted(slow) or "none", bool(skew))
    return True


def clear_topology_penalties() -> None:
    set_topology_penalties((), False)


def penalized_ranks() -> frozenset:
    return _PENALTY["slow_ranks"]


def skew_active() -> bool:
    return bool(_PENALTY["skew"])


def penalty_stamp() -> tuple:
    """Hashable content stamp for schedule memo keys: two identical
    penalty states always produce the same stamp (and digest)."""
    return (tuple(sorted(_PENALTY["slow_ranks"])),
            bool(_PENALTY["skew"]))


def reroot_groups(groups: Sequence[Sequence[int]]) -> list[list]:
    """Re-root a hierarchical group partition away from slow ranks:
    within each group the first non-slow member leads (leader = g[0]
    in ir.hierarchical), and groups whose every member is slow sink to
    the back of the leader chain (leaders[0] is the tree root).
    Relative order is otherwise preserved, so the result — and the
    schedule digest built from it — is deterministic."""
    slow = _PENALTY["slow_ranks"]
    out = [list(g) for g in groups]
    if not slow:
        return out
    rerooted = []
    for g in out:
        fast = [r for r in g if r not in slow]
        rerooted.append(fast + [r for r in g if r in slow])
    rerooted.sort(key=lambda g: 0 if (g and g[0] not in slow) else 1)
    return rerooted


def effective_segments(segments: int) -> int:
    """Segment count under the current penalties: skew doubles the
    segmentation (smaller chunks -> a slow hop stalls less pipeline)."""
    return int(segments) * 2 if _PENALTY["skew"] else int(segments)


# ---------------------------------------------------------------------------
# per-key retune
# ---------------------------------------------------------------------------

def _schedule_id(algo: str, nranks: int) -> str:
    """Like autotune._schedule_id but built through
    ``build_schedule`` so topology penalties reach the recorded
    digest (the generator-level reroot/segment shaping)."""
    from . import ALGOS, ScheduleError, build_schedule

    if algo not in ALGOS:
        return ""
    try:
        return build_schedule(algo, nranks).digest()
    except ScheduleError:
        return ""


def candidate_scores(key: str, *, seed: Optional[int] = None,
                     exclude: Sequence[str] = ()) -> list[dict]:
    """Deterministic model-mode scores for every currently-allowed
    candidate of ``key``, cheapest first. This doubles as the cached
    latency/bandwidth *frontier*: each point carries the step count
    (latency axis) and wire bytes (bandwidth axis) alongside the
    scalar score. Empty when the key doesn't parse or nothing is
    allowed (e.g. every candidate's tier quarantined)."""
    from ..tuned import _algo_space
    from ...ops import lookup as op_lookup
    from . import autotune

    parsed = parse_key(key)
    if parsed is None:
        return []
    seed = autotune._seed_var.value if seed is None else int(seed)
    nbytes = _cache.bucket_bytes(parsed["bucket"])
    nranks = parsed["nranks"]
    dtype = None if parsed["dtype"] == "any" else parsed["dtype"]
    allowed, _skipped = autotune.candidates(
        parsed["opname"], nranks, dtype=dtype, op=op_lookup("sum"))
    known = _algo_space(parsed["opname"])
    drop = set(exclude)
    out = []
    for algo in allowed:
        if algo in drop or algo not in known:
            continue
        steps, wire = autotune._steps_and_wire(algo, nbytes, nranks)
        out.append({
            "algo": algo,
            "score": autotune.model_cost(algo, nbytes, nranks, seed),
            "steps": float(steps),
            "wire": float(wire),
        })
    out.sort(key=lambda c: c["score"])
    return out


def retune_key(key: str, *, reason: str = "drift",
               seed: Optional[int] = None,
               exclude: Sequence[str] = (),
               live_p50_us: Optional[float] = None) -> Optional[dict]:
    """Re-sweep one cache key and install the winner as a
    version-bumped entry (old winner retained for rollback). Returns
    {"key","algorithm","version","previous","reason"} or None when no
    candidate is available. Every install emits a ``sched.retune``
    trace instant and counts ``sched_retunes`` — the retuneaudit lint
    evidence contract."""
    from ...trace import span as tspan

    frontier = candidate_scores(key, seed=seed, exclude=exclude)
    if not frontier:
        SPC.record("sched_retune_failed")
        return None
    parsed = parse_key(key)
    best = frontier[0]
    prev = _cache.CACHE.get(key) or {}
    version = _cache.CACHE.bump(
        key, best["algo"],
        schedule=_schedule_id(best["algo"], parsed["nranks"]),
        source=f"retune:{reason}", score=best["score"],
        frontier=frontier,
    )
    SPC.record("sched_retunes")
    tspan.instant("sched.retune", cat="sched", key=key, reason=reason,
                  algo=best["algo"],
                  prev=prev.get("algorithm", ""), version=version,
                  live_p50_us=live_p50_us)
    logger.info("sched: retuned %s (%s): %s -> %s (v%d)", key, reason,
                prev.get("algorithm", "?"), best["algo"], version)
    return {
        "key": key,
        "algorithm": best["algo"],
        "version": version,
        "previous": prev.get("algorithm", ""),
        "reason": reason,
    }


def reset_for_testing() -> None:
    with _mu:
        _PENALTY["slow_ranks"] = frozenset()
        _PENALTY["skew"] = False
        _PENALTY["gen"] = 0


__all__ = [
    "candidate_scores", "clear_topology_penalties",
    "effective_segments", "parse_key", "penalized_ranks",
    "penalty_stamp", "reroot_groups", "retune_key",
    "reset_for_testing", "set_topology_penalties", "skew_active",
]

"""Schedule IR — declarative chunk/step collective programs.

GC3-style intermediate representation (PAPERS.md: "GC3: An Optimizing
Compiler for GPU Collective Communication"): a collective algorithm is
a ``Schedule`` — a per-rank program of send / reduce / copy steps over
named chunks of the flattened payload, grouped into rounds. The
generators below emit the classic algorithm shapes (ring,
recursive-doubling, segmented ring, hierarchical intra-host /
inter-host, quantized wire) parameterized by the physical topology
(runtime/mesh ring ordering, host grouping); the lowering pass
(sched/lower.py) interprets or tier-maps a Schedule into a fused
jitted callable.

Step kinds:

    send     rank ships chunk to peer this round (value read *after*
             any previous-round mutation of the chunk)
    reduce   rank combines the value received this round into chunk
    copy     rank overwrites chunk with the value received this round
    quant    annotation: the preceding send is wire-quantized
    dequant  annotation: the received value is dequantized before use

Well-formedness (``check``): within one round each rank sends at most
once and receives at most once, every send has a matching receive at
its peer, and chunk ids stay inside [0, nchunks). ``render`` dumps the
step program as text (the tools/sched CLI surface); ``digest`` is the
sha256 of that canonical text — the schedule identity the cache and
validity checker key on.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional, Sequence

KINDS = ("send", "reduce", "copy", "quant", "dequant")

#: Annotation kinds carry no data movement; the interpreter skips them.
ANNOTATIONS = ("quant", "dequant")


@dataclass(frozen=True)
class Step:
    """One IR statement: what ``rank`` does in ``round``."""

    round: int
    kind: str
    rank: int
    peer: int = -1  # -1 on local annotations
    chunk: int = 0

    def render(self) -> str:
        if self.kind == "send":
            return f"r{self.round}: {self.rank}->{self.peer} send c{self.chunk}"
        if self.kind in ("reduce", "copy"):
            return (f"r{self.round}: {self.rank}<-{self.peer} "
                    f"{self.kind} c{self.chunk}")
        return f"r{self.round}: {self.rank} {self.kind} c{self.chunk}"


@dataclass(frozen=True)
class Schedule:
    """A complete chunk/step program for one collective operation.

    ``nchunks`` is the data layout: the payload is flattened and
    zero-padded into ``nchunks`` equal chunks per rank. ``meta`` holds
    the lowering directive (``lowering``: 'interpret' | 'primitive',
    ``tier``: the transport tier of health/ledger's lattice) plus
    generator parameters (order, groups, wire, block, segments).
    """

    name: str
    op: str  # collective family, e.g. "allreduce"
    nranks: int
    nchunks: int
    steps: tuple = ()
    meta: dict = field(default_factory=dict)

    def rounds(self) -> int:
        return 1 + max((s.round for s in self.steps), default=-1)

    def render(self) -> str:
        head = (f"schedule {self.name} op={self.op} nranks={self.nranks} "
                f"nchunks={self.nchunks} rounds={self.rounds()} "
                f"tier={self.meta.get('tier', 'device')} "
                f"lowering={self.meta.get('lowering', 'interpret')}")
        # lowering-relevant generator params must reach the digest (the
        # lowering memo is keyed by it): two schedules with identical
        # steps but different wire codecs are different programs.
        extra = " ".join(
            f"{k}={self.meta[k]}"
            for k in ("primitive", "wire", "block", "segments")
            if k in self.meta
        )
        if extra:
            head = f"{head} {extra}"
        return "\n".join([head] + [s.render() for s in self.steps])

    def digest(self) -> str:
        return hashlib.sha256(self.render().encode()).hexdigest()[:16]


class ScheduleError(ValueError):
    """Malformed schedule program."""


def check(sched: Schedule) -> None:
    """Well-formedness: raise ScheduleError on the first violation."""
    sends: dict[int, dict[int, Step]] = {}
    recvs: dict[int, dict[int, Step]] = {}
    for s in sched.steps:
        if s.kind not in KINDS:
            raise ScheduleError(f"unknown step kind {s.kind!r}: {s}")
        if not 0 <= s.rank < sched.nranks:
            raise ScheduleError(f"rank out of range: {s}")
        if not 0 <= s.chunk < sched.nchunks:
            raise ScheduleError(f"chunk out of range: {s}")
        if s.kind in ANNOTATIONS:
            continue
        if not 0 <= s.peer < sched.nranks:
            raise ScheduleError(f"peer out of range: {s}")
        if s.peer == s.rank:
            raise ScheduleError(f"self-send: {s}")
        table = sends if s.kind == "send" else recvs
        per_round = table.setdefault(s.round, {})
        if s.rank in per_round:
            raise ScheduleError(
                f"rank {s.rank} {'sends' if s.kind == 'send' else 'receives'}"
                f" twice in round {s.round}"
            )
        per_round[s.rank] = s
    for rnd, by_rank in sends.items():
        for s in by_rank.values():
            match = recvs.get(rnd, {}).get(s.peer)
            if match is None or match.peer != s.rank:
                raise ScheduleError(
                    f"send without matching receive at peer: {s}"
                )
    for rnd, by_rank in recvs.items():
        for s in by_rank.values():
            match = sends.get(rnd, {}).get(s.peer)
            if match is None or match.peer != s.rank:
                raise ScheduleError(
                    f"receive without matching send at peer: {s}"
                )


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------

def _order_or_identity(nranks: int, order: Optional[Sequence[int]]
                       ) -> list[int]:
    if order is None:
        return list(range(nranks))
    order = list(order)
    if sorted(order) != list(range(nranks)):
        raise ScheduleError(
            f"order must be a permutation of range({nranks}): {order}"
        )
    return order


def _ring_steps(nranks: int, order: list[int], chunk_base: int = 0,
                round_base: int = 0) -> list[Step]:
    """Reduce-scatter + allgather ring rounds over chunk ids
    [chunk_base, chunk_base + nranks). Position p in the ring is rank
    order[p]; the chunk indices are computed in position space (any
    bijection is correct — every chunk visits every rank)."""
    n = nranks
    steps: list[Step] = []
    for k in range(n - 1):  # reduce-scatter phase
        rnd = round_base + k
        for p in range(n):
            succ = order[(p + 1) % n]
            pred = order[(p - 1) % n]
            steps.append(Step(rnd, "send", order[p], succ,
                              chunk_base + (p - k) % n))
            steps.append(Step(rnd, "reduce", order[p], pred,
                              chunk_base + (p - k - 1) % n))
    for k in range(n - 1):  # allgather phase
        rnd = round_base + n - 1 + k
        for p in range(n):
            succ = order[(p + 1) % n]
            pred = order[(p - 1) % n]
            steps.append(Step(rnd, "send", order[p], succ,
                              chunk_base + (p + 1 - k) % n))
            steps.append(Step(rnd, "copy", order[p], pred,
                              chunk_base + (p - k) % n))
    return steps


def ring(nranks: int, order: Optional[Sequence[int]] = None) -> Schedule:
    """Bandwidth-optimal ring (reference: coll_base_allreduce.c:341):
    n-1 reduce-scatter rounds + n-1 allgather rounds over n chunks.
    ``order`` is the topology-aware ring permutation (mesh.ring_order)
    so consecutive neighbors ride single-hop ICI links."""
    order = _order_or_identity(nranks, order)
    sched = Schedule(
        name="ring", op="allreduce", nranks=nranks, nchunks=nranks,
        steps=tuple(_ring_steps(nranks, order)),
        meta={"tier": "device", "lowering": "interpret", "order": order},
    )
    check(sched)
    return sched


def recursive_doubling(nranks: int) -> Schedule:
    """Butterfly exchange over the full buffer, log2(n) rounds
    (reference: coll_base_allreduce.c:130). Power-of-two rank counts
    only — callers degrade to ring otherwise, as the reference's tuned
    layer does."""
    if nranks & (nranks - 1):
        raise ScheduleError(
            f"recursive_doubling needs a power-of-two rank count, "
            f"got {nranks}"
        )
    steps: list[Step] = []
    k = 0
    dist = 1
    while dist < nranks:
        for r in range(nranks):
            steps.append(Step(k, "send", r, r ^ dist, 0))
            steps.append(Step(k, "reduce", r, r ^ dist, 0))
        dist <<= 1
        k += 1
    sched = Schedule(
        name="recursive_doubling", op="allreduce", nranks=nranks,
        nchunks=1, steps=tuple(steps),
        meta={"tier": "device", "lowering": "interpret"},
    )
    check(sched)
    return sched


def segmented_ring(nranks: int, segments: int,
                   order: Optional[Sequence[int]] = None) -> Schedule:
    """Ring cut into ``segments`` independent chunk ranges (reference:
    coll_base_allreduce.c:618). The rounds of different segments have
    no data dependence between them, so XLA overlaps their ppermutes
    with the combines after jit — the pipelining the reference gets
    from explicit segmentation."""
    if segments < 1:
        raise ScheduleError(f"segments must be >= 1, got {segments}")
    order = _order_or_identity(nranks, order)
    steps: list[Step] = []
    for s in range(segments):
        steps.extend(_ring_steps(nranks, order, chunk_base=s * nranks,
                                 round_base=s * (2 * nranks - 2)))
    sched = Schedule(
        name="segmented_ring", op="allreduce", nranks=nranks,
        nchunks=nranks * segments, steps=tuple(steps),
        meta={"tier": "device", "lowering": "interpret",
              "segments": segments, "order": order},
    )
    check(sched)
    return sched


def reduce_scatter(nranks: int,
                   order: Optional[Sequence[int]] = None) -> Schedule:
    """The reduce-scatter phase of the ring on its own: n-1 rounds over
    n chunks, after which rank r owns the fully reduced chunk r. The
    chunk walk is the first loop of ``_ring_steps`` re-anchored so the
    final reduce at position p lands on chunk order[p] — the rank-owns-
    its-own-index convention of REDUCE_SCATTER_ALGOS."""
    order = _order_or_identity(nranks, order)
    n = nranks
    steps: list[Step] = []
    for k in range(n - 1):
        for p in range(n):
            succ = order[(p + 1) % n]
            pred = order[(p - 1) % n]
            steps.append(Step(k, "send", order[p], succ,
                              order[(p - k - 1) % n]))
            steps.append(Step(k, "reduce", order[p], pred,
                              order[(p - k - 2) % n]))
    sched = Schedule(
        name="reduce_scatter", op="reduce_scatter", nranks=nranks,
        nchunks=nranks, steps=tuple(steps),
        meta={"tier": "device", "lowering": "interpret", "order": order},
    )
    check(sched)
    return sched


def allgather(nranks: int,
              order: Optional[Sequence[int]] = None) -> Schedule:
    """The allgather phase of the ring on its own: n-1 rounds over n
    chunks, starting from the reduce_scatter ownership convention
    (rank order[p] owns fully-reduced chunk order[p]). After the last
    round every rank holds all n chunks — the second half of a
    ZeRO-style RS/AG pair."""
    order = _order_or_identity(nranks, order)
    n = nranks
    steps: list[Step] = []
    for k in range(n - 1):
        for p in range(n):
            succ = order[(p + 1) % n]
            pred = order[(p - 1) % n]
            steps.append(Step(k, "send", order[p], succ,
                              order[(p - k) % n]))
            steps.append(Step(k, "copy", order[p], pred,
                              order[(p - k - 1) % n]))
    sched = Schedule(
        name="allgather", op="allgather", nranks=nranks,
        nchunks=nranks, steps=tuple(steps),
        meta={"tier": "device", "lowering": "interpret", "order": order},
    )
    check(sched)
    return sched


def with_lowering(sched: Schedule, lowering: str, **meta) -> Schedule:
    """The same step program under a different lowering directive (and
    optional extra meta). The digest changes with it — a pallas-lowered
    ring is a different compiled artifact than the interpreted one."""
    import dataclasses

    return dataclasses.replace(
        sched, meta={**sched.meta, "lowering": lowering, **meta})


def hierarchical(groups: Sequence[Sequence[int]]) -> Schedule:
    """Hierarchical allreduce over host groups (the coll/sm + tuned
    split): phase A reduces each group onto its leader (first member),
    phase B chains the leaders (reduce forward, result copy back),
    phase C broadcasts from each leader to its members. Full-buffer
    steps (nchunks=1) — the inter-host phase is latency-bound."""
    groups = [list(g) for g in groups if g]
    if not groups:
        raise ScheduleError("hierarchical needs at least one group")
    nranks = sum(len(g) for g in groups)
    flat = sorted(r for g in groups for r in g)
    if flat != list(range(nranks)):
        raise ScheduleError(
            f"groups must partition range({nranks}): {groups}"
        )
    leaders = [g[0] for g in groups]
    steps: list[Step] = []
    maxlen = max(len(g) for g in groups)
    rnd = 0
    for j in range(maxlen - 1):  # phase A: members -> leader
        for g in groups:
            if len(g) > j + 1:
                steps.append(Step(rnd, "send", g[j + 1], g[0], 0))
                steps.append(Step(rnd, "reduce", g[0], g[j + 1], 0))
        rnd += 1
    for i in range(len(leaders) - 1):  # phase B: leader chain reduce
        steps.append(Step(rnd, "send", leaders[i], leaders[i + 1], 0))
        steps.append(Step(rnd, "reduce", leaders[i + 1], leaders[i], 0))
        rnd += 1
    for i in range(len(leaders) - 1, 0, -1):  # phase B: result back
        steps.append(Step(rnd, "send", leaders[i], leaders[i - 1], 0))
        steps.append(Step(rnd, "copy", leaders[i - 1], leaders[i], 0))
        rnd += 1
    for j in range(maxlen - 1):  # phase C: leader -> members
        for g in groups:
            if len(g) > j + 1:
                steps.append(Step(rnd, "send", g[0], g[j + 1], 0))
                steps.append(Step(rnd, "copy", g[j + 1], g[0], 0))
        rnd += 1
    sched = Schedule(
        name="hierarchical", op="allreduce", nranks=nranks, nchunks=1,
        steps=tuple(steps),
        meta={"tier": "device", "lowering": "interpret",
              "groups": [list(g) for g in groups]},
    )
    check(sched)
    return sched


def quantized_wire(nranks: int, wire: str = "int8", block: int = 128,
                   order: Optional[Sequence[int]] = None) -> Schedule:
    """EQuARX-style quantized-wire ring: the ring step program with
    quant/dequant annotations at every hop. Lowered to the coll/quant
    primitive (the codec and the gate cannot disagree); the step
    program documents exactly where precision is traded for wire
    bytes."""
    order = _order_or_identity(nranks, order)
    base = _ring_steps(nranks, order)
    steps: list[Step] = []
    for s in base:
        if s.kind == "send":
            steps.append(Step(s.round, "quant", s.rank, -1, s.chunk))
            steps.append(s)
        elif s.kind == "reduce":
            steps.append(Step(s.round, "dequant", s.rank, -1, s.chunk))
            steps.append(s)
        else:
            steps.append(s)
    sched = Schedule(
        name="quantized_wire", op="allreduce", nranks=nranks,
        nchunks=nranks, steps=tuple(steps),
        meta={"tier": "device", "lowering": "primitive",
              "primitive": "quant_ring", "wire": wire, "block": block,
              "order": order},
    )
    check(sched)
    return sched


# ---------------------------------------------------------------------------
# multi-collective programs
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ProgramNode:
    """One named sub-collective of a step Program.

    ``deps`` are names of other nodes whose completion gates this
    node's start — the readiness-dependency edge set the overlap
    executor honors (a ZeRO allgather depends on its reduce-scatter;
    a bucket allreduce depends on nothing but its own gradient tiles).
    """

    name: str
    schedule: Schedule
    deps: tuple = ()
    #: forward-consume deadline (slipstream): the step-N+1 layer index
    #: that first reads this node's output, or -1 when unknown.  Enters
    #: the render (and hence the program digest) only when set, so
    #: pre-slipstream programs keep their digests.
    deadline: int = -1

    def render(self) -> str:
        dep = ",".join(self.deps) if self.deps else "-"
        head = f"node {self.name} deps={dep}"
        if self.deadline >= 0:
            head = f"{head} deadline={self.deadline}"
        body = "\n".join("  " + ln
                         for ln in self.schedule.render().splitlines())
        return f"{head}\n{body}"


@dataclass(frozen=True)
class Program:
    """A whole-step communication program: named sub-collectives with
    explicit readiness dependencies between them (GC3's compilation
    unit lifted from one collective to the training step). ``meta``
    carries program-level compile decisions (per-node tile bytes,
    interleave order, RS/AG-vs-allreduce choices) so they reach the
    digest — two programs with the same nodes but different tile
    geometry are different compiled artifacts."""

    name: str
    nranks: int
    nodes: tuple = ()
    meta: dict = field(default_factory=dict)

    def node(self, name: str) -> ProgramNode:
        for n in self.nodes:
            if n.name == name:
                return n
        raise KeyError(name)

    def render(self) -> str:
        head = (f"program {self.name} nranks={self.nranks} "
                f"nodes={len(self.nodes)}")
        extra = " ".join(f"{k}={self.meta[k]}"
                         for k in sorted(self.meta)
                         if isinstance(self.meta[k],
                                       (str, int, float, bool)))
        if extra:
            head = f"{head} {extra}"
        return "\n".join([head] + [n.render() for n in self.nodes])

    def digest(self) -> str:
        return hashlib.sha256(self.render().encode()).hexdigest()[:16]


def check_program(prog: Program) -> None:
    """Program well-formedness: every sub-schedule checks, node names
    are unique, dependency edges resolve to earlier-declared or
    existing nodes, the dep graph is acyclic, and all nodes agree on
    the rank count."""
    names: set[str] = set()
    for node in prog.nodes:
        if node.name in names:
            raise ScheduleError(f"duplicate program node {node.name!r}")
        names.add(node.name)
        if node.schedule.nranks != prog.nranks:
            raise ScheduleError(
                f"node {node.name!r} nranks={node.schedule.nranks} "
                f"!= program nranks={prog.nranks}")
        check(node.schedule)
    for node in prog.nodes:
        for d in node.deps:
            if d not in names:
                raise ScheduleError(
                    f"node {node.name!r} depends on unknown node {d!r}")
            if d == node.name:
                raise ScheduleError(f"node {node.name!r} depends on itself")
    # cycle check: iteratively peel nodes whose deps are all peeled
    remaining = {n.name: set(n.deps) for n in prog.nodes}
    while remaining:
        ready = [k for k, deps in remaining.items()
                 if not deps & set(remaining)]
        if not ready:
            raise ScheduleError(
                f"dependency cycle among program nodes: "
                f"{sorted(remaining)}")
        for k in ready:
            del remaining[k]


def zero_pair(name: str, nranks: int,
              order: Optional[Sequence[int]] = None,
              ag_deadline: Optional[int] = None
              ) -> tuple[ProgramNode, ProgramNode]:
    """A ZeRO-style reduce-scatter + allgather node pair: ``<name>.rs``
    reduces shard order[p] onto rank order[p], ``<name>.ag`` (gated on
    the rs) circulates the reduced shards back out. Together they move
    the same bytes as a ring allreduce but expose the shard-owner
    boundary as a schedulable dependency edge.

    ``ag_deadline`` stamps the allgather node with the step-N+1 forward
    layer that first consumes this bucket's parameters (slipstream's
    residency cost input); it enters the node render and therefore the
    program digest."""
    rs = ProgramNode(name=f"{name}.rs",
                     schedule=reduce_scatter(nranks, order=order))
    ag = ProgramNode(name=f"{name}.ag",
                     schedule=allgather(nranks, order=order),
                     deps=(f"{name}.rs",),
                     deadline=-1 if ag_deadline is None else int(ag_deadline))
    return rs, ag


#: Generator registry for the CLI (`tools/sched dump --name ...`).
GENERATORS = {
    "ring": ring,
    "recursive_doubling": recursive_doubling,
    "segmented_ring": segmented_ring,
    "hierarchical": hierarchical,
    "quantized_wire": quantized_wire,
    "reduce_scatter": reduce_scatter,
    "allgather": allgather,
}


def generate(name: str, nranks: int, **params) -> Schedule:
    """Build a schedule by generator name (CLI entry)."""
    gen = GENERATORS.get(name)
    if gen is None:
        raise ScheduleError(
            f"unknown schedule generator {name!r}; known: "
            f"{sorted(GENERATORS)}"
        )
    if name == "hierarchical":
        groups = params.get("groups") or [list(range(nranks))]
        return gen(groups)
    if name == "segmented_ring":
        return gen(nranks, params.get("segments", 2),
                   order=params.get("order"))
    if name == "quantized_wire":
        return gen(nranks, params.get("wire", "int8"),
                   params.get("block", 128), order=params.get("order"))
    if name in ("ring", "reduce_scatter", "allgather"):
        return gen(nranks, order=params.get("order"))
    return gen(nranks)


__all__ = [
    "ANNOTATIONS", "GENERATORS", "KINDS", "Program", "ProgramNode",
    "Schedule", "ScheduleError", "Step", "allgather", "check",
    "check_program", "generate", "hierarchical", "quantized_wire",
    "recursive_doubling", "reduce_scatter", "ring", "segmented_ring",
    "with_lowering", "zero_pair",
]

"""coll/sched/slipstream — pipeline compiled step programs across the
step boundary.

PR 16 (stepprogram) made the training step the compilation unit, but
each compiled Program still ended at a hard barrier: the merged
per-root broadcast tail drained inside ``finish()`` before step N+1's
backward fired a single tile, and every RS/AG pair allgathered all
parameters even when the next forward would not touch them for many
layers. This module compiles a **two-step sliding window** over the
step IR:

* **The tail becomes a schedulable node.** Step N's merged broadcast
  tail — already a single deferred collective thanks to
  ``partitioned.defer_bcast`` (see ``PartitionedAllreduce.tail_armed``)
  — compiles into an explicit ``s0.tail`` Program node whose readiness
  deps are the step's terminal reduction nodes. Step N+1's nodes
  deliberately carry NO dep on the tail: that missing edge IS the
  overlap, and the session (parallel/overlap, ``window >= 2``)
  dispatches the tail concurrently with step N+1's first backward
  buckets inside the shared ``_batch_window``.
* **Shard residency (ZeRO-2/3).** :func:`compile_window` feeds each
  bucket's ``ag_deadline`` — the step-N+1 forward layer that first
  consumes it — into the autotuner's residency model
  (``autotune.program_node_choice``): buckets whose owner shard can
  stay resident on the optimizer path compile to a lone
  reduce-scatter node, the allgather elided entirely
  (``rs_resident``). The elision and the deadlines land in the program
  meta and node renders, so the digest stays byte-identical across
  same-seed controllers.
* **Fusion spans the boundary.** When the contract holds, the tail's
  dense round-uniform allgather members fuse with step N+1's first
  reduce-scatter group into ONE table program
  (``pallas_lower.fuse_window``, op="window", collective_id 15).

:func:`window_cost_model` is the pure alpha-beta A/B of the two-step
window against the PR 16 barrier — shared with the armada fleet
simulator (sim/engine) so window choices can be costed at 1024 ranks.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ...core.errors import ArgumentError
from . import autotune as _autotune
from . import ir
from . import pallas_lower as _pallas
from .stepprogram import CompiledStep, compile_step


@dataclass(frozen=True)
class CompiledWindow:
    """A two-step sliding window, compiled: the residency-aware step
    program it repeats, the window Program (s0 nodes + s0.tail + s1
    nodes; digest = identity), the fused boundary table program when
    the contract held, and the elision record."""

    step: CompiledStep
    program: ir.Program
    boundary: Optional[ir.Schedule]  # fused tail+next-RS, or None
    elided: tuple       # bucket indices whose allgather was elided
    ag_deadlines: tuple
    nranks: int = 0
    seed: int = 0
    topo_fp: str = ""
    compile_ms: float = 0.0

    def digest(self) -> str:
        return self.program.digest()


def _terminal_name(nd) -> str:
    """The node name whose completion arms a bucket's tail share."""
    return f"b{nd.bucket}.ag" if nd.choice == "rs_ag" else f"b{nd.bucket}"


def compile_window(nranks: int, buckets: Sequence, *,
                   tile_bytes=None, seed: Optional[int] = None,
                   topo_fp: Optional[str] = None,
                   node_choices: Optional[Sequence] = None,
                   ag_deadlines: Optional[Sequence] = None,
                   order: Optional[Sequence] = None,
                   name: str = "window") -> CompiledWindow:
    """Compile a two-step sliding window over one step's bucket list.

    ``ag_deadlines`` defaults to the identity mapping (bucket i's
    parameters are first consumed by forward layer i — the bucketer
    plans buckets in layer order); pass explicit deadlines when the
    next forward's consume order differs. Everything else matches
    :func:`~.stepprogram.compile_step`, which this calls with the
    deadlines threaded through the residency model.

    Deterministic: same (buckets, nranks, seed, cache state) on any
    controller yields a byte-identical window Program render/digest —
    including which allgather nodes were elided and whether the
    boundary fused.
    """
    if not buckets:
        raise ArgumentError("compile_window needs at least one bucket")
    t0 = time.perf_counter()
    if ag_deadlines is None:
        ag_deadlines = tuple(range(len(buckets)))
    else:
        ag_deadlines = tuple(
            None if d is None else int(d) for d in ag_deadlines)
        if len(ag_deadlines) != len(buckets):
            raise ArgumentError(
                f"ag_deadlines has {len(ag_deadlines)} entries for "
                f"{len(buckets)} buckets")
    step = compile_step(
        nranks, buckets, tile_bytes=tile_bytes, seed=seed,
        topo_fp=topo_fp, node_choices=node_choices,
        ag_deadlines=ag_deadlines, order=order, name=f"{name}.step")
    elided = tuple(nd.bucket for nd in step.nodes
                   if nd.choice == "rs_resident")

    # The window program: step N's nodes (s0.*), its broadcast tail as
    # an explicit schedulable node gated on the terminal reduction
    # nodes, then step N+1's nodes (s1.*) with NO dep on the tail —
    # that missing edge is the overlap the executor exploits.
    nodes: list[ir.ProgramNode] = []
    for prefix in ("s0", "s1"):
        for nd in step.program.nodes:
            nodes.append(ir.ProgramNode(
                name=f"{prefix}.{nd.name}", schedule=nd.schedule,
                deps=tuple(f"{prefix}.{d}" for d in nd.deps),
                deadline=nd.deadline))
        if prefix == "s0" and nranks >= 2:
            tail_deps = tuple(
                f"s0.{_terminal_name(nd)}" for nd in step.nodes
                if nd.choice != "rs_resident")
            if tail_deps:
                nodes.append(ir.ProgramNode(
                    name="s0.tail",
                    schedule=ir.allgather(nranks, order=order),
                    deps=tail_deps))
    meta = dict(step.program.meta)
    meta["window"] = 2
    meta["elided"] = (",".join(f"b{i}" for i in elided) if elided
                     else "-")

    # Boundary fusion: the tail's dense round-uniform allgather members
    # with step N+1's first reduce-scatter group, one table program
    # when the contract holds (ArgumentError means "keep per-node
    # kernels for this boundary", never a failed compile).
    boundary = None
    if nranks >= 2:
        tail_ags = [nd.schedule for nd in step.program.nodes
                    if nd.schedule.op == "allgather"]
        next_rs = [nd.schedule for nd in step.program.nodes
                   if nd.schedule.op == "reduce_scatter"]
        if tail_ags and next_rs:
            try:
                boundary = _pallas.fuse_window(
                    f"{name}.boundary", tail_ags, next_rs)
            except ArgumentError:
                boundary = None
    meta["boundary"] = boundary.digest() if boundary is not None else "none"

    program = ir.Program(name=name, nranks=nranks, nodes=tuple(nodes),
                         meta=meta)
    ir.check_program(program)
    return CompiledWindow(
        step=step, program=program, boundary=boundary, elided=elided,
        ag_deadlines=ag_deadlines, nranks=nranks, seed=step.seed,
        topo_fp=step.topo_fp,
        compile_ms=(time.perf_counter() - t0) * 1e3)


def window_cost_model(nranks: int, bucket_nbytes: Sequence[int], *,
                      backward_s: float,
                      coll_time_s: Callable[[str, int], float],
                      seed: Optional[int] = None,
                      ag_deadlines: Optional[Sequence] = None) -> dict:
    """Pure alpha-beta A/B of the two-step window vs the PR 16
    single-step barrier, shared with the armada fleet simulator.

    ``coll_time_s(algo, nbytes)`` prices one collective (the
    simulator passes ``topology.collective_time_s``); a ring
    allreduce's time splits evenly into its reduce half (hidden under
    backward in BOTH arms) and its broadcast-tail half (exposed at the
    barrier, overlapped or elided by the window). Residency decisions
    come from the same ``program_node_choice`` model the compiler
    uses, so the A/B prices exactly the window a controller would
    compile. Deterministic; all floats rounded for digest stability.
    """
    seed = _autotune._seed_var.value if seed is None else int(seed)
    sizes = [int(b) for b in bucket_nbytes]
    if ag_deadlines is None:
        ag_deadlines = tuple(range(len(sizes)))
    tail_all = 0.0      # barrier arm: every bucket's tail share
    tail_window = 0.0   # window arm: non-elided tails only
    elided = 0
    for nbytes, dl in zip(sizes, ag_deadlines):
        share = coll_time_s("ring", nbytes) / 2.0
        tail_all += share
        # The window arm runs the ZeRO pair configuration, so the
        # decision axis priced here is elide-vs-keep the allgather —
        # the same ag_elision_wins model the compiler applies to
        # (pinned or modeled) rs_ag nodes.
        if _autotune.ag_elision_wins(nbytes, nranks, seed, dl):
            elided += 1
        else:
            tail_window += share
    backward_s = float(backward_s)
    # Two steps each: barrier pays the full tail exposed at finish();
    # the window hides step 1's tail under step 2's backward and only
    # exposes the final tail (and any overhang) at flush().
    barrier_s = 2.0 * (backward_s + tail_all)
    window_s = (backward_s + max(backward_s, tail_window)
                + tail_window)
    overlap_s = min(backward_s, tail_window)
    return {
        "nranks": int(nranks),
        "buckets": len(sizes),
        "ag_elided": int(elided),
        "tail_s": round(tail_all, 9),
        "tail_window_s": round(tail_window, 9),
        "tail_overlap_s": round(overlap_s, 9),
        "barrier_s": round(barrier_s, 9),
        "window_s": round(window_s, 9),
        "speedup_x": round(barrier_s / max(window_s, 1e-12), 4),
    }


__all__ = ["CompiledWindow", "compile_window", "window_cost_model"]

"""coll/pallas — hand-scheduled ICI ring collectives as Pallas kernels.

The TPU-native replacement for the reference's explicit algorithm
implementations (reference: ring allreduce coll_base_allreduce.c:341,
ring allgather coll_base_allgather.c, reduce_scatter ring
coll_base_reduce_scatter.c): instead of PML send/recv per round with a
CPU SIMD reduce (ompi/mca/op/avx) between rounds, each kernel drives the
inter-chip DMA engines directly (`pltpu.make_async_remote_copy` over
ICI) and fuses the per-step reduction on the VPU while the next block is
in flight — the compute/communication overlap the segmented-ring
algorithm (coll_base_allreduce.c:618) approximates in software.

Flow control: the two-slot communication buffer is protected by a
capacity semaphore the consumer remote-signals back to its upstream
neighbor after draining a slot; the producer waits before re-filling.
(The reference's analog is the BTL flow-control window / fastbox
`in_use` flags, btl_sm_fbox.h:22-60 — without it a fast sender clobbers
a slot two steps ahead, which we observed in practice.)

These kernels are selected by the `coll/pallas` component (opt-in via
``coll_select=pallas`` or per-op tuned rules); `coll/xla` remains the
default since XLA's own collectives are already ICI-optimal for the
common cases. The kernels run compiled on TPU meshes and in Mosaic
interpret mode on the CPU test mesh (tests/conftest.py's 8 virtual
devices), mirroring the reference's strategy of exercising transport
algorithms over loopback (SURVEY §4).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import config
from ..ops import lookup as op_lookup
from ..ops.op import Op

__all__ = [
    "ring_allgather", "ring_reduce_scatter", "ring_allreduce",
    "ring_allreduce_bidir", "tree_bcast", "ppermute_shift",
]

_interpret_var = config.register(
    "coll", "pallas", "interpret",
    type=bool, default=None,
    description="Force Mosaic interpret mode (auto: on for CPU backend)",
)
_bidir_var = config.register(
    "coll", "pallas", "bidir",
    type=bool, default=False,
    description="Use the bidirectional ring for pallas allreduce "
                "(both ICI link directions per step)",
)


def _interpret():
    """False on TPU (compiled); Mosaic TPU-interpret params on CPU —
    the mode that emulates inter-device DMA + remote semaphore signals
    (plain ``interpret=True`` cannot discharge remote signals)."""
    forced = _interpret_var.value
    if forced is not None and not forced:
        return False
    if forced or jax.default_backend() == "cpu":
        return pltpu.InterpretParams()
    return False


def _combine_blocks(op: Op, a, b):
    """Per-step reduction on the VPU (replaces ompi/mca/op/avx's CPU
    SIMD loops; reference dispatch: op_avx_functions.c:28-66)."""
    return op.combine(a, b)


# ---------------------------------------------------------------------------
# Kernels. All operate on a (n, chunk) view: the leading axis indexes
# ring positions (rank blocks), `chunk` is the flattened payload slice.
# ---------------------------------------------------------------------------

def _allgather_kernel(axis_name: str, n: int, local_ref, out_ref,
                      comm_buf, send_sem, recv_sem, cap_sem):
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    out_ref[me] = local_ref[:]
    comm_buf[0] = local_ref[:]

    for step in range(n - 1):
        slot = step % 2
        nslot = (step + 1) % 2
        # Backpressure: the slot we are about to fill downstream was
        # last filled at step-2; wait until the consumer drained it.
        if step >= 2:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        src_block = jax.lax.rem(me - step - 1 + n, n)
        out_ref[src_block] = comm_buf[nslot]
        # Drained comm_buf[nslot]; let upstream reuse it at step+2.
        if step < n - 3:
            pltpu.semaphore_signal(
                cap_sem.at[nslot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )


def _reduce_scatter_kernel(axis_name: str, n: int, op: Op, x_ref, out_ref,
                           comm_buf, send_sem, recv_sem, cap_sem):
    """Ring reduce-scatter (the first phase of the reference's ring
    allreduce, coll_base_allreduce.c:341): at step s, pass the partial
    for block (me - s - 1) to the right, reducing on arrival; after
    n-1 steps each rank holds the full reduction of block me."""
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    # Start the chain with our partial for the block owned by our left
    # neighbor's ... standard schedule: send block (me - 1), so that
    # block b circulates from rank b+1 around to rank b, accumulating.
    first = jax.lax.rem(me - 1 + n, n)
    comm_buf[0] = x_ref[first]

    for step in range(n - 1):
        slot = step % 2
        nslot = (step + 1) % 2
        if step >= 2:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        # Arrived: partial sum for block (me - step - 2) ... derive from
        # schedule: we received what left sent = left's block index
        # (left - step - 1) = me - step - 2.
        blk = jax.lax.rem(me - step - 2 + 2 * n, n)
        reduced = _combine_blocks(op, comm_buf[nslot], x_ref[blk])
        if step < n - 2:
            comm_buf[nslot] = reduced
            if step < n - 3:
                pltpu.semaphore_signal(
                    cap_sem.at[nslot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
        else:
            out_ref[:] = reduced


def _allreduce_kernel(axis_name: str, n: int, op: Op, x_ref, out_ref,
                      comm_buf, send_sem, recv_sem, cap_sem):
    """Ring allreduce = reduce-scatter phase + allgather phase in one
    kernel (2(n-1) steps, the bandwidth-optimal schedule the tuned
    decision layer picks for large commutative reductions —
    coll_tuned_decision_fixed.c:45-87)."""
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    first = jax.lax.rem(me - 1 + n, n)
    comm_buf[0] = x_ref[first]

    for step in range(2 * (n - 1)):
        slot = step % 2
        nslot = (step + 1) % 2
        if step >= 2:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if step < n - 1:
            # reduce-scatter phase
            blk = jax.lax.rem(me - step - 2 + 2 * n, n)
            val = _combine_blocks(op, comm_buf[nslot], x_ref[blk])
            comm_buf[nslot] = val
            # The block completed at the last RS step (blk == me) is the
            # first fully-reduced one; store it before the AG phase.
            if step == n - 2:
                out_ref[blk] = val
        else:
            # allgather phase: circulate the fully-reduced blocks.
            blk = jax.lax.rem(me - (step - (n - 1)) - 1 + 2 * n, n)
            out_ref[blk] = comm_buf[nslot]
        if step < 2 * (n - 1) - 2:
            pltpu.semaphore_signal(
                cap_sem.at[nslot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )


# ---------------------------------------------------------------------------
# Host-callable wrappers (shard_map bodies). Input per shard: the local
# (n, chunk) contribution view.
# ---------------------------------------------------------------------------

def _sems():
    return [
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
    ]


def _pad_chunk(x: jax.Array) -> tuple[jax.Array, int, tuple]:
    """Flatten to (lanes,) padded to the f32 tile quantum so VMEM
    blocks tile cleanly (pallas_guide: min tile (8,128) for f32)."""
    orig_shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % 128
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, pad, orig_shape


def ring_allgather(x: jax.Array, axis_name: str) -> jax.Array:
    """Inside shard_map: local block (chunk,) -> gathered (n, chunk)."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x[None]
    flat, pad, shape = _pad_chunk(x)
    kernel = functools.partial(_allgather_kernel, axis_name, n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, flat.size), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, flat.size), flat.dtype)] + _sems(),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def ring_reduce_scatter(x: jax.Array, axis_name: str, op: Any = "sum"
                        ) -> jax.Array:
    """Inside shard_map: local (n, chunk) contributions -> own reduced
    block (chunk,)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x[0]
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    lanes = flat.shape[1]
    pad = (-lanes) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_reduce_scatter_kernel, axis_name, n, op)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.shape[1],), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, flat.shape[1]), flat.dtype)] + _sems(),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=1,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def ring_allreduce(x: jax.Array, axis_name: str, op: Any = "sum"
                   ) -> jax.Array:
    """Inside shard_map: local (n, chunk) contributions -> fully
    reduced (n, chunk) (every block identical across ranks only in the
    rank-major world view; here each rank returns all blocks)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_allreduce_kernel, axis_name, n, op)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, flat.shape[1]), flat.dtype)] + _sems(),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=2,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def _allreduce_bidir_kernel(axis_name: str, n: int, op: Op, half: int,
                            x_ref, out_ref, buf_a, buf_b,
                            ssem_a, rsem_a, csem_a,
                            ssem_b, rsem_b, csem_b):
    """Bidirectional ring allreduce: the payload splits in half and the
    two halves run the 2(n-1)-step ring schedule in OPPOSITE directions
    simultaneously, so both ICI directions of the torus link carry data
    every step — 2x the link bandwidth of the unidirectional ring
    (reference's algorithm space has only the one-direction ring,
    coll_base_allreduce.c:341; this is the TPU-topology upgrade).
    Both directions' DMAs are started before either is awaited."""
    me = jax.lax.axis_index(axis_name)
    parts = (
        (1, buf_a, ssem_a, rsem_a, csem_a, slice(0, half)),
        (-1, buf_b, ssem_b, rsem_b, csem_b, slice(half, None)),
    )
    for d, buf, _ss, _rs, _cs, sl in parts:
        first = jax.lax.rem(me - d + n, n)
        buf[0] = x_ref[first, sl]

    for step in range(2 * (n - 1)):
        slot = step % 2
        nslot = (step + 1) % 2
        descs = []
        for d, buf, ssem, rsem, csem, sl in parts:
            if step >= 2:
                pltpu.semaphore_wait(csem.at[nslot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf.at[slot],
                dst_ref=buf.at[nslot],
                send_sem=ssem.at[slot],
                recv_sem=rsem.at[nslot],
                device_id=jax.lax.rem(me + d + n, n),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()  # both directions in flight together
            descs.append(rdma)
        for (d, buf, ssem, rsem, csem, sl), rdma in zip(parts, descs):
            rdma.wait()
            if step < n - 1:
                blk = jax.lax.rem(me - d * (step + 2) + 3 * n, n)
                val = _combine_blocks(op, buf[nslot], x_ref[blk, sl])
                buf[nslot] = val
                if step == n - 2:
                    out_ref[blk, sl] = val  # blk == me: first done block
            else:
                blk = jax.lax.rem(
                    me - d * (step - (n - 1) + 1) + 3 * n, n
                )
                out_ref[blk, sl] = buf[nslot]
            if step < 2 * (n - 1) - 2:
                pltpu.semaphore_signal(
                    csem.at[nslot], inc=1,
                    device_id=jax.lax.rem(me - d + n, n),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )


def _tree_bcast_kernel(axis_name: str, n: int, root: int,
                       x_ref, out_ref, send_sem, recv_sem, ready_sem):
    """Binomial-tree bcast: in round k every rank that already holds
    the payload (relative rank < 2^k) pushes it one subtree over
    (relative +2^k) — ceil(log2 n) rounds total (reference:
    ompi_coll_base_bcast_intra_binomial, coll_base_bcast.c; tree shape
    coll_base_topo.c). Asymmetric DMA: senders wait send completion,
    receivers park on the recv semaphore (wait_recv). The receiver
    remote-signals readiness to its sender BEFORE parking — the DMA
    targets the same out_ref the receiver initializes at kernel start,
    and with skewed kernel-start times an unsynchronized send could
    land before that init overwrites it."""
    me = jax.lax.axis_index(axis_name)
    rel = jax.lax.rem(me - root + n, n)
    out_ref[:] = x_ref[:]
    rounds = max(1, (n - 1).bit_length())
    for k in range(rounds):
        bit = 1 << k
        dst = jax.lax.rem(me + bit, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref,
            dst_ref=out_ref,
            send_sem=send_sem.at[k % 2],
            recv_sem=recv_sem.at[k % 2],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        is_recv = jnp.logical_and(rel >= bit, rel < 2 * bit)

        @pl.when(is_recv)
        def _ready():
            # my sender is relative -bit: tell it my out_ref is ready
            pltpu.semaphore_signal(
                ready_sem.at[k % 2], inc=1,
                device_id=jax.lax.rem(me - bit + n, n),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        @pl.when(jnp.logical_and(rel < bit, rel + bit < n))
        def _send(rdma=rdma):
            pltpu.semaphore_wait(ready_sem.at[k % 2], 1)
            rdma.start()
            rdma.wait_send()

        @pl.when(is_recv)
        def _recv(rdma=rdma):
            rdma.wait_recv()


def ring_allreduce_bidir(x: jax.Array, axis_name: str, op: Any = "sum"
                         ) -> jax.Array:
    """Inside shard_map: local (n, chunk) contributions -> fully
    reduced (n, chunk) via the bidirectional ring (both ICI link
    directions active every step)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 256  # two 128-lane-aligned halves
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    lanes = flat.shape[1]
    half = lanes // 2
    kernel = functools.partial(
        _allreduce_bidir_kernel, axis_name, n, op, half
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, half), flat.dtype),
            pltpu.VMEM((2, lanes - half), flat.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=6,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def tree_bcast(x: jax.Array, axis_name: str, root: int = 0
               ) -> jax.Array:
    """Inside shard_map: local block -> root's block, binomial tree."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    flat, pad, shape = _pad_chunk(x)
    kernel = functools.partial(_tree_bcast_kernel, axis_name, n,
                               int(root))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.size,), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=5,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def _alltoall_kernel(axis_name: str, n: int, x_ref, out_ref,
                     send_sem, recv_sem):
    """Pairwise-exchange alltoall (reference: coll_base_alltoall.c's
    pairwise variant): at step s every rank RDMA-writes block
    (me+s) directly into rank (me+s)'s out[me] — no intermediate
    buffering, each byte crosses ICI exactly once. The EP/Ulysses
    primitive (SURVEY §2.6, §5.7)."""
    me = jax.lax.axis_index(axis_name)
    out_ref[me] = x_ref[me]
    for step in range(1, n):
        dst = jax.lax.rem(me + step, n)
        slot = step % 2
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[dst],
            dst_ref=out_ref.at[me],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()


def ring_alltoall(x: jax.Array, axis_name: str) -> jax.Array:
    """Inside shard_map: local (n, chunk) send blocks -> (n, chunk)
    received blocks (row s = block from rank s)."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_alltoall_kernel, axis_name, n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)), pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=4,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def ppermute_shift(x: jax.Array, axis_name: str, shift: int = 1
                   ) -> jax.Array:
    """One ring hop as a Pallas remote DMA — the building block for
    ring attention's rotating KV blocks (SURVEY §5.7 plan: 'ring
    send-recv Pallas kernel with double-buffered ICI DMA')."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    flat, pad, shape = _pad_chunk(x)

    def kernel(local_ref, out_ref, send_sem, recv_sem):
        me = jax.lax.axis_index(axis_name)
        dst = jax.lax.rem(me + shift + n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=local_ref,
            dst_ref=out_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.size,), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=3,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# Component: comm-vtable entry points over the kernels. Each rank's
# buffer is split into n ring segments so the schedule pipelines the
# whole payload (the reference's ring operates on per-rank blocks the
# same way, coll_base_allreduce.c:341).
# ---------------------------------------------------------------------------

from .framework import COLL, CollComponent, compile_plan, rank_major_check  # noqa: E402


def _split_ring(b: jax.Array, n: int) -> tuple[jax.Array, int, tuple]:
    shape = b.shape
    flat = b.reshape(-1)
    pad = (-flat.size) % (n * 128)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, -1), pad, shape


def _unsplit_ring(blocks: jax.Array, pad: int, shape: tuple) -> jax.Array:
    flat = blocks.reshape(-1)
    if pad:
        flat = flat[:-pad]
    return flat.reshape(shape)


def allreduce_block(b: jax.Array, axis_name: str, op: Any) -> jax.Array:
    """shard_map body: rank's contribution -> fully reduced buffer."""
    n = jax.lax.axis_size(axis_name)
    segs, pad, shape = _split_ring(b, n)
    out = ring_allreduce(segs, axis_name, op)
    return _unsplit_ring(out, pad, shape)


def allreduce_block_bidir(b: jax.Array, axis_name: str, op: Any
                          ) -> jax.Array:
    """shard_map body for the bidirectional ring."""
    n = jax.lax.axis_size(axis_name)
    segs, pad, shape = _split_ring(b, n)
    out = ring_allreduce_bidir(segs, axis_name, op)
    return _unsplit_ring(out, pad, shape)


def bcast_block(b: jax.Array, axis_name: str, root: int = 0
                ) -> jax.Array:
    """shard_map body: every rank ends with root's block (binomial
    tree over ICI DMA)."""
    return tree_bcast(b, axis_name, root=root)


@COLL.register
class PallasColl(CollComponent):
    NAME = "pallas"
    PRIORITY = 30  # below coll/xla (40): opt-in via coll_select/priority
    DESCRIPTION = "hand-scheduled ICI ring kernels (Pallas remote DMA)"

    def allreduce(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x
        body = allreduce_block_bidir if _bidir_var.value \
            else allreduce_block
        key = ("allreduce", "pallas", body.__name__, op.cache_key,
               x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: body(b, "ranks", op),
            check_vma=False,
        )
        return plan(x)

    def bcast(self, comm, x, root):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x
        key = ("bcast", "pallas", root, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: bcast_block(b, "ranks", root=root),
            check_vma=False,
        )
        return plan(x)

    def allgather(self, comm, x):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None]
        key = ("allgather", "pallas", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: ring_allgather(b, "ranks"),
            check_vma=False,
        )
        return plan(x)

    def reduce_scatter_block(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x, min_ndim=2)
        if comm.size == 1:
            return x[:, 0]
        key = ("reduce_scatter_block", "pallas", op.cache_key, x.shape,
               str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: ring_reduce_scatter(b, "ranks", op),
            check_vma=False,
        )
        return plan(x)

    def alltoall(self, comm, x):
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            from ..core.errors import ArgumentError

            raise ArgumentError(
                f"alltoall needs (size, size, ...) buffer, got {x.shape}"
            )
        if comm.size == 1:
            return x
        key = ("alltoall", "pallas", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: ring_alltoall(b, "ranks"),
            check_vma=False,
        )
        return plan(x)

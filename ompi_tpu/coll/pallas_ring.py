"""coll/pallas — hand-scheduled ICI ring collectives as Pallas kernels.

The TPU-native replacement for the reference's explicit algorithm
implementations (reference: ring allreduce coll_base_allreduce.c:341,
ring allgather coll_base_allgather.c, reduce_scatter ring
coll_base_reduce_scatter.c): instead of PML send/recv per round with a
CPU SIMD reduce (ompi/mca/op/avx) between rounds, each kernel drives the
inter-chip DMA engines directly (`pltpu.make_async_remote_copy` over
ICI) and fuses the per-step reduction on the VPU while the next block is
in flight — the compute/communication overlap the segmented-ring
algorithm (coll_base_allreduce.c:618) approximates in software.

Flow control: the two-slot communication buffer is protected by a
capacity semaphore the consumer remote-signals back to its upstream
neighbor after draining a slot; the producer waits before re-filling.
(The reference's analog is the BTL flow-control window / fastbox
`in_use` flags, btl_sm_fbox.h:22-60 — without it a fast sender clobbers
a slot two steps ahead, which we observed in practice.)

These kernels are selected by the `coll/pallas` component (opt-in via
``coll_select=pallas`` or per-op tuned rules); `coll/xla` remains the
default since XLA's own collectives are already ICI-optimal for the
common cases. The kernels run compiled on TPU meshes and in Mosaic
interpret mode on the CPU test mesh (tests/conftest.py's 8 virtual
devices), mirroring the reference's strategy of exercising transport
algorithms over loopback (SURVEY §4).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import config
from ..ops import lookup as op_lookup
from ..ops.op import Op

__all__ = [
    "ring_allgather", "ring_reduce_scatter", "ring_allreduce",
    "ring_allreduce_bidir", "ring_allreduce_chunked", "ring_allreduce_rd",
    "tree_bcast", "tree_reduce", "linear_gather", "linear_scatter",
    "ppermute_shift",
]

_interpret_var = config.register(
    "coll", "pallas", "interpret",
    type=bool, default=None,
    description="Force Mosaic interpret mode (auto: on for CPU backend)",
)
_bidir_var = config.register(
    "coll", "pallas", "bidir",
    type=bool, default=False,
    description="Use the bidirectional ring for pallas allreduce "
                "(both ICI link directions per step)",
)
_segment_var = config.register(
    "coll", "pallas", "segment_bytes",
    type=int, default=1 << 20,
    description="Segment size for the chunked HBM-streaming ring "
                "kernels (reference's segmented-ring knob: 1 MiB, "
                "coll_tuned_decision_fixed.c:73)",
)
_chunk_threshold_var = config.register(
    "coll", "pallas", "chunk_threshold_bytes",
    type=int, default=4 << 20,
    description="Per-shard payload size above which pallas allreduce "
                "streams segments HBM->VMEM (chunked kernel) instead "
                "of staging the whole payload in VMEM",
)
_rd_cutoff_var = config.register(
    "coll", "pallas", "rd_cutoff_bytes",
    type=int, default=10_000,
    description="Per-shard bytes below which pallas allreduce uses "
                "recursive doubling (reference: 10000B cutoff, "
                "coll_tuned_decision_fixed.c:53)",
)


def interpret_available() -> bool:
    """Does this jax build ship Mosaic's TPU interpret mode (the
    inter-device DMA + remote semaphore emulation)? 0.4.x builds do
    not — there the pallas kernels only run on real TPU hardware, and
    CPU-tier validation falls back to the sched compiler's table
    simulator (sched/pallas_lower.simulate)."""
    return hasattr(pltpu, "InterpretParams")


def _interpret():
    """False on TPU (compiled); Mosaic TPU-interpret params on CPU —
    the mode that emulates inter-device DMA + remote semaphore signals
    (plain ``interpret=True`` cannot discharge remote signals)."""
    forced = _interpret_var.value
    if forced is not None and not forced:
        return False
    if forced or jax.default_backend() == "cpu":
        if not interpret_available():
            raise RuntimeError(
                "this jax build has no Mosaic TPU interpret mode "
                "(pltpu.InterpretParams); pallas kernels need a TPU "
                "backend or jax >= 0.5")
        return pltpu.InterpretParams()
    return False


def _combine_blocks(op: Op, a, b):
    """Per-step reduction on the VPU (replaces ompi/mca/op/avx's CPU
    SIMD loops; reference dispatch: op_avx_functions.c:28-66)."""
    return op.combine(a, b)


# ---------------------------------------------------------------------------
# Kernels. All operate on a (n, chunk) view: the leading axis indexes
# ring positions (rank blocks), `chunk` is the flattened payload slice.
# ---------------------------------------------------------------------------

def _allgather_kernel(axis_name: str, n: int, local_ref, out_ref,
                      comm_buf, send_sem, recv_sem, cap_sem):
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    out_ref[me] = local_ref[:]
    comm_buf[0] = local_ref[:]
    # Post-seed credit: gates the upstream neighbor's step-1 write into
    # comm_buf[0] so a fast neighbor cannot land it before the seed
    # (kernel-start skew; there is no implicit entry barrier). A
    # 2-member ring has no step 1 in this n-1-step schedule — emitting
    # the credit would leave cap_sem[0] non-zero at kernel exit.
    if n > 2:
        pltpu.semaphore_signal(cap_sem.at[0], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    for step in range(n - 1):
        slot = step % 2
        nslot = (step + 1) % 2
        # Backpressure: wait for the downstream credit before filling
        # its slot (step 1: the post-seed credit; later steps: the
        # consumer drained the slot two steps ago).
        if step >= 1:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        src_block = jax.lax.rem(me - step - 1 + n, n)
        out_ref[src_block] = comm_buf[nslot]
        # Drained comm_buf[nslot]; let upstream reuse it at step+2.
        if step < n - 3:
            pltpu.semaphore_signal(
                cap_sem.at[nslot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )


def _reduce_scatter_kernel(axis_name: str, n: int, op: Op, x_ref, out_ref,
                           comm_buf, send_sem, recv_sem, cap_sem):
    """Ring reduce-scatter (the first phase of the reference's ring
    allreduce, coll_base_allreduce.c:341): at step s, pass the partial
    for block (me - s - 1) to the right, reducing on arrival; after
    n-1 steps each rank holds the full reduction of block me."""
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    # Start the chain with our partial for the block owned by our left
    # neighbor's ... standard schedule: send block (me - 1), so that
    # block b circulates from rank b+1 around to rank b, accumulating.
    first = jax.lax.rem(me - 1 + n, n)
    comm_buf[0] = x_ref[first]
    # Post-seed credit gating the upstream step-1 write (see allgather;
    # same n==2 exclusion — the n-1-step schedule has no step 1 there).
    if n > 2:
        pltpu.semaphore_signal(cap_sem.at[0], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    for step in range(n - 1):
        slot = step % 2
        nslot = (step + 1) % 2
        if step >= 1:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        # Arrived: partial sum for block (me - step - 2) ... derive from
        # schedule: we received what left sent = left's block index
        # (left - step - 1) = me - step - 2.
        blk = jax.lax.rem(me - step - 2 + 2 * n, n)
        reduced = _combine_blocks(op, comm_buf[nslot], x_ref[blk])
        if step < n - 2:
            comm_buf[nslot] = reduced
            if step < n - 3:
                pltpu.semaphore_signal(
                    cap_sem.at[nslot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
        else:
            out_ref[:] = reduced


def _allreduce_kernel(axis_name: str, n: int, op: Op, x_ref, out_ref,
                      comm_buf, send_sem, recv_sem, cap_sem):
    """Ring allreduce = reduce-scatter phase + allgather phase in one
    kernel (2(n-1) steps, the bandwidth-optimal schedule the tuned
    decision layer picks for large commutative reductions —
    coll_tuned_decision_fixed.c:45-87)."""
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    first = jax.lax.rem(me - 1 + n, n)
    comm_buf[0] = x_ref[first]
    # Post-seed credit gating the upstream step-1 write (see allgather).
    pltpu.semaphore_signal(cap_sem.at[0], inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)

    for step in range(2 * (n - 1)):
        slot = step % 2
        nslot = (step + 1) % 2
        if step >= 1:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_buf.at[slot],
            dst_ref=comm_buf.at[nslot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        if step < n - 1:
            # reduce-scatter phase
            blk = jax.lax.rem(me - step - 2 + 2 * n, n)
            val = _combine_blocks(op, comm_buf[nslot], x_ref[blk])
            comm_buf[nslot] = val
            # The block completed at the last RS step (blk == me) is the
            # first fully-reduced one; store it before the AG phase.
            if step == n - 2:
                out_ref[blk] = val
        else:
            # allgather phase: circulate the fully-reduced blocks.
            blk = jax.lax.rem(me - (step - (n - 1)) - 1 + 2 * n, n)
            out_ref[blk] = comm_buf[nslot]
        if step < 2 * (n - 1) - 2:
            pltpu.semaphore_signal(
                cap_sem.at[nslot], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )


# ---------------------------------------------------------------------------
# Chunked (HBM-streaming) ring allreduce: the reference's segmented ring
# (coll_base_allreduce.c:618-717 — 1 MiB segments pipelined through a
# bounded buffer) re-built for the TPU memory hierarchy. The payload
# stays in HBM; the VMEM working set is six segment-sized slots (input
# prefetch x2, comm buffer x2, output stage x2), so shard sizes are
# bounded by HBM, not the ~16 MiB VMEM. Data is laid out (n, rows, 128)
# so every slot slice is a cleanly tiled 2-D block (Mosaic rejects
# dim-0 slices of 2-D buffers that break the (8,128) tiling — found by
# compiling on hardware).
#
# Flow control has two levels: within a segment, the capacity semaphore
# of the plain ring kernels; across segments, a credit semaphore — a
# device may start sending segment i+1 only after its downstream
# neighbor signals it has drained segment i (the reference's analog is
# the bounded num_segments pipeline in the segmented ring). One credit
# is primed at kernel start and the residue drained at kernel end so
# every segment's wait is unconditional (no predicated semaphore ops).
# ---------------------------------------------------------------------------

def _sublane(dtype) -> int:
    """Minimum second-to-last-dim tile for the dtype (pallas_guide:
    (8,128) f32, (16,128) bf16, (32,128) int8)."""
    return max(8, 32 // max(1, jnp.dtype(dtype).itemsize))


def _allreduce_chunked_kernel(axis_name: str, n: int, op: Op, seg: int,
                              n_segs: int, x_hbm, out_hbm,
                              comm_buf, x_buf, out_buf,
                              send_sem, recv_sem, cap_sem,
                              x_sem, out_sem, seg_sem):
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    # Prime one segment credit so every segment (incl. 0) waits uniformly.
    pltpu.semaphore_signal(seg_sem, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)

    def seg_body(si, _):
        off = si * seg

        # Credit from the right neighbor: it drained our previous
        # segment's sends from its comm buffer.
        pltpu.semaphore_wait(seg_sem, 1)

        def x_dma(j, slot):
            # j-th needed input block for this rank's ring schedule:
            # j=0 seeds the comm buffer, j=s+1 is combined at RS step s.
            blk = jax.lax.rem(me - 1 - j + 2 * n, n)
            return pltpu.make_async_copy(
                x_hbm.at[blk, pl.ds(off, seg)], x_buf.at[slot],
                x_sem.at[slot])

        def out_dma(blk, slot):
            return pltpu.make_async_copy(
                out_buf.at[slot], out_hbm.at[blk, pl.ds(off, seg)],
                out_sem.at[slot])

        x_dma(0, 0).start()
        x_dma(1, 1).start()
        x_dma(0, 0).wait()
        comm_buf[0] = x_buf[0]
        # Post-seed credit: the upstream neighbor's step-1 remote write
        # lands in comm_buf[0] — the slot the seed just filled. Without
        # this credit a fast left neighbor (already credited for the
        # next segment at our previous segment's end) could write
        # comm_buf[0] BEFORE the seed, which then silently overwrites
        # the delivered partial (the recv semaphore count would still
        # satisfy our step-1 wait). Gate every step-1 send on it.
        pltpu.semaphore_signal(cap_sem.at[0], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

        writes = []  # in-flight VMEM->HBM output copies (unrolled)
        for step in range(2 * (n - 1)):
            slot = step % 2
            nslot = (step + 1) % 2
            if step >= 1:
                pltpu.semaphore_wait(cap_sem.at[nslot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nslot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nslot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            # Prefetch the input block for the NEXT reduce-scatter step
            # while the remote DMA is in flight; its slot held block
            # `step`, consumed at the previous step.
            if step + 2 < n:
                x_dma(step + 2, step % 2).start()
            rdma.wait()
            if step < n - 1:
                # reduce-scatter phase: fold our block into the arrival
                x_dma(step + 1, (step + 1) % 2).wait()
                blk = jax.lax.rem(me - step - 2 + 2 * n, n)
                val = _combine_blocks(op, comm_buf[nslot],
                                      x_buf[(step + 1) % 2])
                comm_buf[nslot] = val
                if step == n - 2:  # blk == me: first fully-reduced block
                    wslot = len(writes) % 2
                    if len(writes) >= 2:
                        writes[-2].wait()
                    out_buf[wslot] = val
                    writes.append(out_dma(blk, wslot))
                    writes[-1].start()
            else:
                # allgather phase: stream fully-reduced blocks out
                blk = jax.lax.rem(me - (step - (n - 1)) - 1 + 2 * n, n)
                wslot = len(writes) % 2
                if len(writes) >= 2:
                    writes[-2].wait()
                out_buf[wslot] = comm_buf[nslot]
                writes.append(out_dma(blk, wslot))
                writes[-1].start()
            if step < 2 * (n - 1) - 2:
                pltpu.semaphore_signal(
                    cap_sem.at[nslot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
        # Drained every send from the left neighbor: grant next credit.
        pltpu.semaphore_signal(seg_sem, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        # Out-copies must land before their slots are reused next segment.
        for w in writes[-2:]:
            w.wait()
        return 0

    jax.lax.fori_loop(0, n_segs, seg_body, 0)
    # Consume the residual credit (prime + n_segs signals, n_segs waits).
    pltpu.semaphore_wait(seg_sem, 1)


def _selfdma_chunked_kernel(axis_name: str, seg: int, n_segs: int,
                            x_hbm, out_hbm,
                            x_buf, comm_buf, x_sem, send_sem, recv_sem,
                            out_sem):
    """Degenerate 1-member ring of the chunked schedule: per segment,
    HBM->VMEM prefetch, one self-targeted remote DMA (the ICI machinery
    with device_id == me), VMEM->HBM writeback — double-buffered. This
    is the bench's on-chip Mosaic proof path: a 1-rank allreduce is the
    identity, but every DMA engine the n>1 schedule uses runs for real.

    3-stage software pipeline: the remote DMA of segment si is waited
    only at iteration si+1, so IN(si+1), RDMA(si) and OUT(si-1) are all
    in flight together (a back-to-back start/wait serialized the three
    engines and capped the measured HBM rate at ~half the roofline).
    Slot hazards: RDMA(si) needs comm_buf[si%2] free -> OUT(si-2)
    waited; IN(si+1) needs x_buf[(si+1)%2] free -> RDMA(si-1) waited;
    OUT(si) needs RDMA(si) waited."""
    def in_dma(si):
        return pltpu.make_async_copy(
            x_hbm.at[0, pl.ds(si * seg, seg)], x_buf.at[si % 2],
            x_sem.at[si % 2])

    def out_dma(si):
        return pltpu.make_async_copy(
            comm_buf.at[si % 2], out_hbm.at[0, pl.ds(si * seg, seg)],
            out_sem.at[si % 2])

    def rdma(si):
        slot = si % 2
        return pltpu.make_async_remote_copy(
            src_ref=x_buf.at[slot], dst_ref=comm_buf.at[slot],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[slot],
            device_id=jax.lax.axis_index(axis_name),
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    in_dma(0).start()
    if n_segs > 1:
        in_dma(1).start()
    for si in range(n_segs):
        in_dma(si).wait()
        if si >= 2:
            out_dma(si - 2).wait()  # comm_buf[si%2] reader must finish
        rdma(si).start()
        if si >= 1:
            rdma(si - 1).wait()
            out_dma(si - 1).start()
            if si + 1 < n_segs:
                in_dma(si + 1).start()  # x_buf slot freed by the wait
    rdma(n_segs - 1).wait()
    out_dma(n_segs - 1).start()
    for si in range(max(0, n_segs - 2), n_segs):
        out_dma(si).wait()


def ring_allreduce_chunked(x: jax.Array, axis_name: str, op: Any = "sum",
                           seg_bytes: int | None = None) -> jax.Array:
    """Inside shard_map: this rank's full contribution (any shape) ->
    fully reduced buffer of the same shape, streamed through VMEM in
    double-buffered segments. Unlike the whole-payload kernels, handles
    shards far larger than VMEM (the reference's segmented ring regime,
    coll_base_allreduce.c:618)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if seg_bytes is None:
        seg_bytes = _segment_var.value
    shape = x.shape
    flat = x.reshape(-1)
    itemsize = jnp.dtype(flat.dtype).itemsize
    a = _sublane(flat.dtype)

    # Lay out as (n, rows, 128): rows aligned to the sublane tile and
    # to a whole number of segments.
    rows = -(-flat.size // (n * 128))
    rows = -(-rows // a) * a
    seg_rows = max(a, min(-(-rows // a) * a,
                          (seg_bytes // (128 * itemsize) // a) * a or a))
    rows = -(-rows // seg_rows) * seg_rows
    n_segs = rows // seg_rows
    pad = n * rows * 128 - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(n, rows, 128)

    if n == 1:
        kernel = functools.partial(_selfdma_chunked_kernel, axis_name,
                                   seg_rows, n_segs)
        scratch = [
            pltpu.VMEM((2, seg_rows, 128), flat.dtype),
            pltpu.VMEM((2, seg_rows, 128), flat.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        # collective_id must be absent on a 1-member ring (no barrier).
        params = pltpu.CompilerParams(has_side_effects=True)
    else:
        kernel = functools.partial(_allreduce_chunked_kernel, axis_name,
                                   n, op, seg_rows, n_segs)
        scratch = [
            pltpu.VMEM((2, seg_rows, 128), flat.dtype),
            pltpu.VMEM((2, seg_rows, 128), flat.dtype),
            pltpu.VMEM((2, seg_rows, 128), flat.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ]
        params = pltpu.CompilerParams(has_side_effects=True,
                                      collective_id=7)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, rows, 128), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=_interpret(),
    )(blocks)
    flat_out = out.reshape(-1)
    if pad:
        flat_out = flat_out[:-pad]
    return flat_out.reshape(shape)


# ---------------------------------------------------------------------------
# Host-callable wrappers (shard_map bodies). Input per shard: the local
# (n, chunk) contribution view.
# ---------------------------------------------------------------------------

def _sems():
    return [
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
    ]


def _pad_chunk(x: jax.Array) -> tuple[jax.Array, int, tuple]:
    """Flatten to (lanes,) padded to the f32 tile quantum so VMEM
    blocks tile cleanly (pallas_guide: min tile (8,128) for f32)."""
    orig_shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % 128
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, pad, orig_shape


def ring_allgather(x: jax.Array, axis_name: str) -> jax.Array:
    """Inside shard_map: local block (chunk,) -> gathered (n, chunk)."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x[None]
    flat, pad, shape = _pad_chunk(x)
    kernel = functools.partial(_allgather_kernel, axis_name, n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, flat.size), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, flat.size), flat.dtype)] + _sems(),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def ring_reduce_scatter(x: jax.Array, axis_name: str, op: Any = "sum"
                        ) -> jax.Array:
    """Inside shard_map: local (n, chunk) contributions -> own reduced
    block (chunk,)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x[0]
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    lanes = flat.shape[1]
    pad = (-lanes) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_reduce_scatter_kernel, axis_name, n, op)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.shape[1],), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, flat.shape[1]), flat.dtype)] + _sems(),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=1,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def ring_allreduce(x: jax.Array, axis_name: str, op: Any = "sum"
                   ) -> jax.Array:
    """Inside shard_map: local (n, chunk) contributions -> fully
    reduced (n, chunk) (every block identical across ranks only in the
    rank-major world view; here each rank returns all blocks)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_allreduce_kernel, axis_name, n, op)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, flat.shape[1]), flat.dtype)] + _sems(),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=2,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def _allreduce_bidir_kernel(axis_name: str, n: int, op: Op, half: int,
                            x_ref, out_ref, buf_a, buf_b,
                            ssem_a, rsem_a, csem_a,
                            ssem_b, rsem_b, csem_b):
    """Bidirectional ring allreduce: the payload splits in half and the
    two halves run the 2(n-1)-step ring schedule in OPPOSITE directions
    simultaneously, so both ICI directions of the torus link carry data
    every step — 2x the link bandwidth of the unidirectional ring
    (reference's algorithm space has only the one-direction ring,
    coll_base_allreduce.c:341; this is the TPU-topology upgrade).
    Both directions' DMAs are started before either is awaited."""
    me = jax.lax.axis_index(axis_name)
    parts = (
        (1, buf_a, ssem_a, rsem_a, csem_a, slice(0, half)),
        (-1, buf_b, ssem_b, rsem_b, csem_b, slice(half, None)),
    )
    for d, buf, _ss, _rs, csem, sl in parts:
        first = jax.lax.rem(me - d + n, n)
        buf[0] = x_ref[first, sl]
        # Post-seed credit to this direction's upstream (see allgather).
        pltpu.semaphore_signal(
            csem.at[0], inc=1, device_id=jax.lax.rem(me - d + n, n),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    for step in range(2 * (n - 1)):
        slot = step % 2
        nslot = (step + 1) % 2
        descs = []
        for d, buf, ssem, rsem, csem, sl in parts:
            if step >= 1:
                pltpu.semaphore_wait(csem.at[nslot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=buf.at[slot],
                dst_ref=buf.at[nslot],
                send_sem=ssem.at[slot],
                recv_sem=rsem.at[nslot],
                device_id=jax.lax.rem(me + d + n, n),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()  # both directions in flight together
            descs.append(rdma)
        for (d, buf, ssem, rsem, csem, sl), rdma in zip(parts, descs):
            rdma.wait()
            if step < n - 1:
                blk = jax.lax.rem(me - d * (step + 2) + 3 * n, n)
                val = _combine_blocks(op, buf[nslot], x_ref[blk, sl])
                buf[nslot] = val
                if step == n - 2:
                    out_ref[blk, sl] = val  # blk == me: first done block
            else:
                blk = jax.lax.rem(
                    me - d * (step - (n - 1) + 1) + 3 * n, n
                )
                out_ref[blk, sl] = buf[nslot]
            if step < 2 * (n - 1) - 2:
                pltpu.semaphore_signal(
                    csem.at[nslot], inc=1,
                    device_id=jax.lax.rem(me - d + n, n),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )


def _allreduce_rd_kernel(axis_name: str, n: int, op: Op,
                         x_ref, out_ref, comm_buf, send_sems, recv_sems):
    """Recursive-doubling allreduce (reference:
    ompi_coll_base_allreduce_intra_recursivedoubling,
    coll_base_allreduce.c:130): log2(n) rounds, each exchanging the FULL
    payload with partner me^2^k — the latency-optimal schedule tuned
    picks below the 10 KB cutoff. Round k gets its own comm slot AND its
    own semaphore pair: partners of different rounds live in disjoint
    hypercube blocks until they meet, so a fast subtree can run rounds
    ahead — per-round semaphores keep its early DMA from satisfying an
    earlier round's wait (slot-mod-2 sharing would)."""
    me = jax.lax.axis_index(axis_name)
    out_ref[:] = x_ref[:]
    rounds = (n - 1).bit_length()
    for k in range(rounds):
        bit = 1 << k
        partner = me ^ bit
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref,
            dst_ref=comm_buf.at[k],
            send_sem=send_sems.at[k],
            recv_sem=recv_sems.at[k],
            device_id=partner,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()

        # Rank-ordered combine so non-commutative user ops see the MPI
        # reduction order (the reference's is_commutative branch).
        @pl.when(partner < me)
        def _lower():
            out_ref[:] = _combine_blocks(op, comm_buf[k], out_ref[:])

        @pl.when(partner >= me)
        def _upper():
            out_ref[:] = _combine_blocks(op, out_ref[:], comm_buf[k])


def ring_allreduce_rd(x: jax.Array, axis_name: str, op: Any = "sum"
                      ) -> jax.Array:
    """Inside shard_map: full local contribution -> fully reduced buffer
    via recursive doubling (power-of-two axis sizes only, like the
    reference's variant)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    if n & (n - 1):
        raise ValueError(
            f"recursive doubling needs a power-of-two ring, got {n}"
        )
    flat, pad, shape = _pad_chunk(x)
    rounds = (n - 1).bit_length()
    kernel = functools.partial(_allreduce_rd_kernel, axis_name, n, op)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.size,), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rounds, flat.size), flat.dtype),
            pltpu.SemaphoreType.DMA((rounds,)),
            pltpu.SemaphoreType.DMA((rounds,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=8,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def _tree_reduce_kernel(axis_name: str, n: int, root: int, op: Op,
                        x_ref, out_ref, comm_buf, send_sems, recv_sems):
    """Binomial-tree reduce-to-root (reference:
    ompi_coll_base_reduce_intra_binomial, coll_base_reduce.c): the
    mirror of the bcast tree — in round k, every rank whose relative
    rank has lowest set bit 2^k sends its accumulated subtree to
    relative rank rel-2^k and leaves the game; receivers fold arrivals
    in ascending subtree order. Per-round buffers + semaphores for the
    same skew reason as the rd kernel."""
    me = jax.lax.axis_index(axis_name)
    rel = jax.lax.rem(me - root + n, n)
    out_ref[:] = x_ref[:]
    rounds = (n - 1).bit_length()
    for k in range(rounds):
        bit = 1 << k
        low = rel & (2 * bit - 1)
        is_send = low == bit
        is_recv = jnp.logical_and(low == 0, rel + bit < n)
        dst = jax.lax.rem(me - bit + n, n)  # sender's parent
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref,
            dst_ref=comm_buf.at[k],
            send_sem=send_sems.at[k],
            recv_sem=recv_sems.at[k],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

        @pl.when(is_send)
        def _send(rdma=rdma):
            rdma.start()
            rdma.wait_send()

        @pl.when(is_recv)
        def _recv(rdma=rdma):
            rdma.wait_recv()
            # arrival comes from rel+bit: higher relative rank, so the
            # accumulator stays on the left of the fold
            out_ref[:] = _combine_blocks(op, out_ref[:], comm_buf[k])


def tree_reduce(x: jax.Array, axis_name: str, op: Any = "sum",
                root: int = 0) -> jax.Array:
    """Inside shard_map: full local contribution -> the reduction at
    root (other ranks return their partial accumulator — MPI semantics:
    recvbuf significant only at root)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    flat, pad, shape = _pad_chunk(x)
    rounds = (n - 1).bit_length()
    kernel = functools.partial(_tree_reduce_kernel, axis_name, n,
                               int(root), op)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.size,), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rounds, flat.size), flat.dtype),
            pltpu.SemaphoreType.DMA((rounds,)),
            pltpu.SemaphoreType.DMA((rounds,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=9,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def _tree_bcast_kernel(axis_name: str, n: int, root: int,
                       x_ref, out_ref, send_sem, recv_sem, ready_sem):
    """Binomial-tree bcast: in round k every rank that already holds
    the payload (relative rank < 2^k) pushes it one subtree over
    (relative +2^k) — ceil(log2 n) rounds total (reference:
    ompi_coll_base_bcast_intra_binomial, coll_base_bcast.c; tree shape
    coll_base_topo.c). Asymmetric DMA: senders wait send completion,
    receivers park on the recv semaphore (wait_recv). The receiver
    remote-signals readiness to its sender BEFORE parking — the DMA
    targets the same out_ref the receiver initializes at kernel start,
    and with skewed kernel-start times an unsynchronized send could
    land before that init overwrites it."""
    me = jax.lax.axis_index(axis_name)
    rel = jax.lax.rem(me - root + n, n)
    out_ref[:] = x_ref[:]
    rounds = max(1, (n - 1).bit_length())
    for k in range(rounds):
        bit = 1 << k
        dst = jax.lax.rem(me + bit, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref,
            dst_ref=out_ref,
            send_sem=send_sem.at[k % 2],
            recv_sem=recv_sem.at[k % 2],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        is_recv = jnp.logical_and(rel >= bit, rel < 2 * bit)

        @pl.when(is_recv)
        def _ready():
            # my sender is relative -bit: tell it my out_ref is ready
            pltpu.semaphore_signal(
                ready_sem.at[k % 2], inc=1,
                device_id=jax.lax.rem(me - bit + n, n),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

        @pl.when(jnp.logical_and(rel < bit, rel + bit < n))
        def _send(rdma=rdma):
            pltpu.semaphore_wait(ready_sem.at[k % 2], 1)
            rdma.start()
            rdma.wait_send()

        @pl.when(is_recv)
        def _recv(rdma=rdma):
            rdma.wait_recv()


def ring_allreduce_bidir(x: jax.Array, axis_name: str, op: Any = "sum"
                         ) -> jax.Array:
    """Inside shard_map: local (n, chunk) contributions -> fully
    reduced (n, chunk) via the bidirectional ring (both ICI link
    directions active every step)."""
    op = op_lookup(op)
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 256  # two 128-lane-aligned halves
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    lanes = flat.shape[1]
    half = lanes // 2
    kernel = functools.partial(
        _allreduce_bidir_kernel, axis_name, n, op, half
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, half), flat.dtype),
            pltpu.VMEM((2, lanes - half), flat.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=6,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def tree_bcast(x: jax.Array, axis_name: str, root: int = 0
               ) -> jax.Array:
    """Inside shard_map: local block -> root's block, binomial tree."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    flat, pad, shape = _pad_chunk(x)
    kernel = functools.partial(_tree_bcast_kernel, axis_name, n,
                               int(root))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.size,), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=5,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def _alltoall_kernel(axis_name: str, n: int, x_ref, out_ref,
                     send_sem, recv_sem):
    """Pairwise-exchange alltoall (reference: coll_base_alltoall.c's
    pairwise variant): at step s every rank RDMA-writes block
    (me+s) directly into rank (me+s)'s out[me] — no intermediate
    buffering, each byte crosses ICI exactly once. The EP/Ulysses
    primitive (SURVEY §2.6, §5.7). Each step has its OWN semaphore
    pair: the writer of my out at step s is (me-s), a different device
    each step with no transitive ordering, so a 2-slot rotation would
    let a fast peer's later-step write satisfy an earlier step's wait
    and the kernel could exit before the straggler lands."""
    me = jax.lax.axis_index(axis_name)
    out_ref[me] = x_ref[me]
    for step in range(1, n):
        dst = jax.lax.rem(me + step, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=x_ref.at[dst],
            dst_ref=out_ref.at[me],
            send_sem=send_sem.at[step - 1],
            recv_sem=recv_sem.at[step - 1],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()


def ring_alltoall(x: jax.Array, axis_name: str) -> jax.Array:
    """Inside shard_map: local (n, chunk) send blocks -> (n, chunk)
    received blocks (row s = block from rank s)."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_alltoall_kernel, axis_name, n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.DMA((n - 1,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=4,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def _gather_kernel(axis_name: str, n: int, root: int, x_ref, out_ref,
                   send_sems, recv_sems, ready_sem):
    """Linear gather-to-root (reference: coll_base_gather.c,
    ompi_coll_base_gather_intra_basic_linear): every non-root rank
    remote-DMAs its block into root's out[me]; root initializes its own
    row, grants a readiness credit to each sender (its out buffer is
    live), then parks on one recv semaphore per sender. Distinct
    semaphore slots per sender — the writers are unordered peers, so a
    shared slot could let one fast sender satisfy another's wait (same
    reasoning as the pairwise alltoall kernel)."""
    me = jax.lax.axis_index(axis_name)
    rel = jax.lax.rem(me - root + n, n)

    @pl.when(rel == 0)
    def _root():
        out_ref[me] = x_ref[:]
        for s in range(1, n):
            pltpu.semaphore_signal(
                ready_sem, inc=1, device_id=jax.lax.rem(root + s, n),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
        for s in range(1, n):
            src_dev = jax.lax.rem(root + s, n)
            pltpu.make_async_remote_copy(
                src_ref=x_ref, dst_ref=out_ref.at[src_dev],
                send_sem=send_sems.at[s - 1],
                recv_sem=recv_sems.at[s - 1],
                device_id=src_dev,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            ).wait_recv()

    @pl.when(rel != 0)
    def _sender():
        pltpu.semaphore_wait(ready_sem, 1)
        # slot rel-1 matches the descriptor root waits on
        for s in range(1, n):
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref, dst_ref=out_ref.at[me],
                send_sem=send_sems.at[s - 1],
                recv_sem=recv_sems.at[s - 1],
                device_id=root,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

            @pl.when(rel == s)
            def _go(rdma=rdma):
                rdma.start()
                rdma.wait_send()


def linear_gather(x: jax.Array, axis_name: str, root: int = 0
                  ) -> jax.Array:
    """Inside shard_map: local block (chunk,) -> (n, chunk), rows
    defined at root only (MPI gather semantics)."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x[None]
    flat, pad, shape = _pad_chunk(x)
    kernel = functools.partial(_gather_kernel, axis_name, n, int(root))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, flat.size), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=10,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:, :-pad]
    return out.reshape((n,) + shape)


def _scatter_kernel(axis_name: str, n: int, root: int, x_ref, out_ref,
                    send_sems, recv_sems):
    """Linear scatter-from-root (reference: coll_base_scatter.c,
    ompi_coll_base_scatter_intra_basic_linear): root pushes row s of its
    buffer into rank (root+s)'s out. No readiness handshake needed —
    receivers never write their landing buffer, they only read it after
    the recv semaphore fires, so an early-landing DMA is harmless."""
    me = jax.lax.axis_index(axis_name)
    rel = jax.lax.rem(me - root + n, n)

    @pl.when(rel == 0)
    def _root():
        out_ref[:] = x_ref[me]
        rdmas = []
        for s in range(1, n):
            dst_dev = jax.lax.rem(root + s, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[dst_dev], dst_ref=out_ref,
                send_sem=send_sems.at[s - 1],
                recv_sem=recv_sems.at[s - 1],
                device_id=dst_dev,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdmas.append(rdma)
        for rdma in rdmas:
            rdma.wait_send()

    @pl.when(rel != 0)
    def _receiver():
        for s in range(1, n):
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[me], dst_ref=out_ref,
                send_sem=send_sems.at[s - 1],
                recv_sem=recv_sems.at[s - 1],
                device_id=root,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )

            @pl.when(rel == s)
            def _take(rdma=rdma):
                rdma.wait_recv()


def linear_scatter(x: jax.Array, axis_name: str, root: int = 0
                   ) -> jax.Array:
    """Inside shard_map: (n, chunk) buffer (significant at root) ->
    own block (chunk,)."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x[0]
    shape = x.shape[1:]
    flat = x.reshape(n, -1)
    pad = (-flat.shape[1]) % 128
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    kernel = functools.partial(_scatter_kernel, axis_name, n, int(root))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.shape[1],), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.DMA((n - 1,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=11,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


def ppermute_shift(x: jax.Array, axis_name: str, shift: int = 1
                   ) -> jax.Array:
    """One ring hop as a Pallas remote DMA — the building block for
    ring attention's rotating KV blocks (SURVEY §5.7 plan: 'ring
    send-recv Pallas kernel with double-buffered ICI DMA')."""
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        return x
    flat, pad, shape = _pad_chunk(x)

    def kernel(local_ref, out_ref, send_sem, recv_sem):
        me = jax.lax.axis_index(axis_name)
        dst = jax.lax.rem(me + shift + n, n)
        rdma = pltpu.make_async_remote_copy(
            src_ref=local_ref,
            dst_ref=out_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((flat.size,), flat.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=3,
        ),
        interpret=_interpret(),
    )(flat)
    if pad:
        out = out[:-pad]
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# Component: comm-vtable entry points over the kernels. Each rank's
# buffer is split into n ring segments so the schedule pipelines the
# whole payload (the reference's ring operates on per-rank blocks the
# same way, coll_base_allreduce.c:341).
# ---------------------------------------------------------------------------

from .framework import COLL, CollComponent, compile_plan, rank_major_check  # noqa: E402


def _split_ring(b: jax.Array, n: int) -> tuple[jax.Array, int, tuple]:
    shape = b.shape
    flat = b.reshape(-1)
    pad = (-flat.size) % (n * 128)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, -1), pad, shape


def _unsplit_ring(blocks: jax.Array, pad: int, shape: tuple) -> jax.Array:
    flat = blocks.reshape(-1)
    if pad:
        flat = flat[:-pad]
    return flat.reshape(shape)


def allreduce_block(b: jax.Array, axis_name: str, op: Any) -> jax.Array:
    """shard_map body: rank's contribution -> fully reduced buffer."""
    n = jax.lax.axis_size(axis_name)
    segs, pad, shape = _split_ring(b, n)
    out = ring_allreduce(segs, axis_name, op)
    return _unsplit_ring(out, pad, shape)


def allreduce_block_bidir(b: jax.Array, axis_name: str, op: Any
                          ) -> jax.Array:
    """shard_map body for the bidirectional ring."""
    n = jax.lax.axis_size(axis_name)
    segs, pad, shape = _split_ring(b, n)
    out = ring_allreduce_bidir(segs, axis_name, op)
    return _unsplit_ring(out, pad, shape)


def allreduce_block_chunked(b: jax.Array, axis_name: str, op: Any
                            ) -> jax.Array:
    """shard_map body for the chunked HBM-streaming ring (shards larger
    than VMEM; reference regime: segmented ring,
    coll_base_allreduce.c:618)."""
    return ring_allreduce_chunked(b, axis_name, op)


def allreduce_block_rd(b: jax.Array, axis_name: str, op: Any
                       ) -> jax.Array:
    """shard_map body for recursive doubling (small-message regime;
    reference: <10 KB cutoff, coll_tuned_decision_fixed.c:53)."""
    return ring_allreduce_rd(b, axis_name, op)


def reduce_block(b: jax.Array, axis_name: str, op: Any, root: int = 0
                 ) -> jax.Array:
    """shard_map body for binomial-tree reduce-to-root."""
    return tree_reduce(b, axis_name, op, root=root)


def allreduce_block_rsag(b: jax.Array, axis_name: str, op: Any
                         ) -> jax.Array:
    """Two-phase allreduce composed from the standalone reduce-scatter
    and allgather ring kernels. Communication-equivalent to the fused
    ring (2(n-1) steps, 1/n payload each) — NOT the reference's
    log(n) halving/doubling Rabenseifner (coll_base_allreduce.c:970) —
    but it exercises the standalone kernels as a pipeline stage pair,
    which is how TP layers consume them (psum_scatter + all_gather)."""
    n = jax.lax.axis_size(axis_name)
    segs, pad, shape = _split_ring(b, n)
    own = ring_reduce_scatter(segs, axis_name, op)
    out = ring_allgather(own, axis_name)
    return _unsplit_ring(out, pad, shape)


def bcast_block(b: jax.Array, axis_name: str, root: int = 0
                ) -> jax.Array:
    """shard_map body: every rank ends with root's block (binomial
    tree over ICI DMA)."""
    return tree_bcast(b, axis_name, root=root)


def gather_block(b: jax.Array, axis_name: str, root: int = 0
                 ) -> jax.Array:
    """shard_map body: own block -> (n, ...) gathered rows (defined at
    root), linear gather over ICI DMA."""
    return linear_gather(b, axis_name, root=root)


def scatter_block(b: jax.Array, axis_name: str, root: int = 0
                  ) -> jax.Array:
    """shard_map body: (n, ...) buffer (significant at root) -> own
    block, linear scatter over ICI DMA."""
    return linear_scatter(b, axis_name, root=root)


@COLL.register
class PallasColl(CollComponent):
    NAME = "pallas"
    PRIORITY = 30  # below coll/xla (40): opt-in via coll_select/priority
    DESCRIPTION = "hand-scheduled ICI ring kernels (Pallas remote DMA)"

    def allreduce(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x
        shard_bytes = (x.size // comm.size) * x.dtype.itemsize
        pof2 = comm.size & (comm.size - 1) == 0
        if shard_bytes > _chunk_threshold_var.value:
            # Large payloads stream HBM->VMEM in segments; the
            # whole-payload kernels would blow the ~16 MiB VMEM.
            body = allreduce_block_chunked
        elif shard_bytes < _rd_cutoff_var.value and pof2:
            # small-message latency regime: log2(n) rounds beats the
            # ring's 2(n-1) (reference 10 KB cutoff)
            body = allreduce_block_rd
        elif _bidir_var.value:
            body = allreduce_block_bidir
        else:
            body = allreduce_block
        key = ("allreduce", "pallas", body.__name__, op.cache_key,
               x.shape, str(x.dtype))
        if body is allreduce_block_chunked:
            # the segment size is baked into the traced kernel; a knob
            # change must not hit a stale plan
            key = key + (int(_segment_var.value),)
        plan = compile_plan(
            comm, key, lambda b: body(b, "ranks", op),
            check_vma=False,
        )
        return plan(x)

    def reduce(self, comm, x, op, root):
        """Binomial tree reduce over ICI DMA; result block at root
        (reference: coll_base_reduce.c binomial)."""
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[0] if x.shape[0] == 1 else x[root]
        if not getattr(op, "commutative", True):
            # rank-ordered fallback (reference: non-commutative ops take
            # the linear path, coll_tuned_decision_fixed.c:85)
            return COLL.component("basic").reduce(comm, x, op, root)
        key = ("reduce", "pallas", "tree", op.cache_key, root, x.shape,
               str(x.dtype))
        plan = compile_plan(
            comm, key,
            lambda b: reduce_block(b, "ranks", op, root=root),
            check_vma=False,
        )
        return plan(x)[root]

    def bcast(self, comm, x, root):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x
        key = ("bcast", "pallas", root, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: bcast_block(b, "ranks", root=root),
            check_vma=False,
        )
        return plan(x)

    def allgather(self, comm, x):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None]
        key = ("allgather", "pallas", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: ring_allgather(b, "ranks"),
            check_vma=False,
        )
        return plan(x)

    def reduce_scatter_block(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x, min_ndim=2)
        if comm.size == 1:
            return x[:, 0]
        key = ("reduce_scatter_block", "pallas", op.cache_key, x.shape,
               str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: ring_reduce_scatter(b, "ranks", op),
            check_vma=False,
        )
        return plan(x)

    def gather(self, comm, x, root):
        """Linear gather over ICI DMA; rows defined at root
        (reference: coll_base_gather.c basic_linear)."""
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None][root]
        key = ("gather", "pallas", root, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: gather_block(b, "ranks", root=root),
            check_vma=False,
        )
        return plan(x)[root]

    def scatter(self, comm, x, root):
        """Linear scatter over ICI DMA (reference: coll_base_scatter.c
        basic_linear). Root's (size, ...) buffer is staged rank-major
        (replicated rows) so the kernel sees it on-device."""
        from ..core.errors import ArgumentError

        arr = jnp.asarray(x)
        if arr.shape[0] != comm.size:
            raise ArgumentError(
                f"scatter needs (size, ...) buffer, got {arr.shape}"
            )
        if comm.size == 1:
            # rank-major (1,)+row result, matching XlaColl/TunedColl
            return comm.put_rank_major(arr)
        stacked = comm.put_rank_major(
            jnp.broadcast_to(arr[None], (comm.size,) + arr.shape)
        )
        key = ("scatter", "pallas", root, stacked.shape, str(stacked.dtype))
        plan = compile_plan(
            comm, key, lambda b: scatter_block(b, "ranks", root=root),
            check_vma=False,
        )
        return plan(stacked)

    def alltoall(self, comm, x):
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            from ..core.errors import ArgumentError

            raise ArgumentError(
                f"alltoall needs (size, size, ...) buffer, got {x.shape}"
            )
        if comm.size == 1:
            return x
        key = ("alltoall", "pallas", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: ring_alltoall(b, "ranks"),
            check_vma=False,
        )
        return plan(x)

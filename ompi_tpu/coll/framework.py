"""The coll framework: per-communicator collective selection + plans.

TPU-native equivalent of ompi/mca/coll's framework base (reference:
coll.h:480 `collm_comm_query`, coll.h:629-702 per-comm function table,
coll_base_comm_select.c:110-152 highest-priority-per-function merge).

Driver-mode collectives operate on "rank-major" buffers: jax.Arrays with
leading axis == comm.size, sharded one block per rank-device. Each
component lowers an operation to a *plan* — a jitted shard_map program
over the comm's 1-D mesh — cached per (operation, algorithm, shape,
dtype) on the communicator. Plan reuse is the latency strategy: the
reference re-runs its decision + schedule machinery per call (ob1 fastbox
/ sendi tricks, SURVEY §7); here the steady-state call is a single cached
XLA executable launch.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from ..core import component as mca
from ..core import config
from ..core.errors import ArgumentError, CommError
from ..core.logging import get_logger
from ..core.request import Request, Status
from ..ops import Op, lookup as op_lookup

logger = get_logger("coll")

# Collective operations a component may provide (reference enumerates 22
# in coll_base_functions.h:45-66; the nonblocking/persistent variants are
# derived from these at the communicator layer).
OPERATIONS = (
    "allreduce",
    "bcast",
    "reduce",
    "allgather",
    "reduce_scatter_block",
    "alltoall",
    "gather",
    "scatter",
    "scan",
    "exscan",
    "barrier",
    # vector (per-rank counts) variants, reference
    # coll_base_functions.h:75-76 (alltoallv/w) and the *v family
    "allgatherv",
    "gatherv",
    "scatterv",
    "alltoallv",
    "alltoallw",
    "reduce_scatter",
    # neighborhood collectives over the comm topology, reference
    # coll_base_functions.h:62-66
    "neighbor_allgather",
    "neighbor_alltoall",
)

COLL = mca.framework("coll", "collective operations")


class CollComponent(mca.Component):
    """Base class: a coll component provides a subset of OPERATIONS as
    methods fn(comm, *args)."""

    def provided(self) -> list[str]:
        return [op for op in OPERATIONS if hasattr(self, op)]

    def persistent_program(self, comm, opname: str, x, args):
        """Pre-bound dispatch for persistent collectives: return
        ``prog(buffer) -> pending`` with every per-call decision
        (validation, algorithm choice, cache-key build, plan lookup)
        already resolved against (comm, args) — or None when the
        operation has no clean single-plan form (e.g. root-sliced
        reduce, ragged variants). PersistentColl binds the program on
        first start(); every subsequent start() is then one plan
        launch, skipping the vtable/_coll_call path entirely (the
        pcollreq promise: MPI_Start must be cheaper than a fresh
        call)."""
        return None


def select_for_comm(comm) -> dict[str, tuple[Any, Callable]]:
    """Merge per-operation tables: for each op, the highest-priority
    available component that implements it (the reference's merge loop,
    coll_base_comm_select.c:110-152)."""
    ensure_components()
    table: dict[str, tuple[Any, Callable]] = {}
    for comp in COLL.select_all(comm=comm):
        for opname in comp.provided():
            if opname not in table:
                table[opname] = (comp, getattr(comp, opname))
    if comm.size > 0 and len(table) < len(OPERATIONS):
        missing = [o for o in OPERATIONS if o not in table]
        logger.info("comm %s missing coll ops: %s", comm.name, missing)
    # faultline interposes at selection (sanitizer pattern): when a
    # fault plan is armed, every vtable entry consults it on dispatch.
    from ..ft import inject

    table = inject.maybe_wrap_coll(table)
    # commtrace wraps outermost: every dispatch runs under a span whose
    # trace_id all ranks derive identically (trace/span.py). The
    # component half of each entry stays unwrapped.
    from ..trace import span as tspan

    return tspan.maybe_wrap_coll(table)


# ---------------------------------------------------------------------------
# Plan compilation
# ---------------------------------------------------------------------------

def compile_plan(
    comm,
    key: tuple,
    per_rank_fn: Callable,
    *,
    donate: bool = False,
    check_vma: bool = True,
) -> Callable:
    """Build (or fetch) the jitted shard_map program applying
    ``per_rank_fn(block)`` on every rank's leading-axis block."""
    cache = comm._plan_cache
    plan = cache.get(key)
    if plan is not None:
        return plan

    import jax
    from jax.sharding import PartitionSpec as P

    from ..core import jax_compat

    jax_compat.ensure()

    mesh = comm.mesh

    def wrapped(block):
        squeezed = jax.tree.map(lambda b: b[0], block)
        res = per_rank_fn(squeezed)
        return jax.tree.map(lambda r: r[None], res)

    # check_vma=False is for pallas plans only: pallas_call outputs
    # mix varying and replicated values that trip jax's vma tracking
    # (jax's documented workaround); other components keep the check.
    fn = jax.shard_map(
        wrapped, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
        check_vma=check_vma,
    )
    plan = jax.jit(fn, donate_argnums=(0,) if donate else ())
    cache[key] = plan
    from ..core.counters import SPC

    SPC.record("coll_plans_compiled")
    return plan


def rank_major_check(comm, x, min_ndim: int = 1):
    import jax.numpy as jnp

    arr = jnp.asarray(x)
    if arr.ndim < min_ndim or arr.shape[0] != comm.size:
        raise ArgumentError(
            f"expected rank-major buffer with leading dim {comm.size}, "
            f"got shape {arr.shape}"
        )
    return arr


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------

class DeviceRequest(Request):
    """A nonblocking collective: the device work is already enqueued by
    JAX async dispatch; completion == result arrays ready."""

    def __init__(self, result: Any) -> None:
        super().__init__()
        self._pending = result

    def _leaves(self):
        import jax

        return [
            leaf
            for leaf in jax.tree.leaves(self._pending)
            if hasattr(leaf, "is_ready")
        ]

    def _poll(self) -> bool:
        if self.done:
            return True
        if all(leaf.is_ready() for leaf in self._leaves()):
            self._complete(self._pending)
        return self.done

    def wait(self, timeout: float | None = None) -> Status:
        import jax

        from ..core import progress as _progress

        if not self.done:
            if timeout is None:
                jax.block_until_ready(self._pending)
                self._complete(self._pending)
            elif not _progress.ENGINE.progress_until(self._poll, timeout):
                raise TimeoutError("collective wait timed out")
        return self.status


class PersistentColl(Request):
    """Persistent collective (MPI_Allreduce_init / pcollreq extension):
    binds (comm, operation, args); each start() re-dispatches the cached
    plan against the bound buffer."""

    def __init__(self, comm, opname: str, args: tuple, x: Any) -> None:
        super().__init__(persistent=True)
        self._comm = comm
        self._opname = opname
        self._args = args
        self.buffer = x
        self._pending = None
        self._dispatch = None  # resolved once, on first start()
        # Interned at construction: start() is the latency-critical
        # call (persistent_start_us bench row) and must do no per-call
        # string building or allocation beyond the dispatch itself.
        self._spc_name = f"coll_persistent_{opname}_starts"

    def bind(self, x: Any) -> None:
        """Rebind the input buffer (same shape/dtype reuses the plan)."""
        self.buffer = x

    def _resolve(self) -> None:
        """First-start binding: ask the providing component for a
        pre-bound program; fall back to a direct (vtable-resolved once)
        component call for operations without a plan form. Either way,
        later starts never re-enter _coll_call — no vtable lookup, no
        SPC/memchecker/monitor interposition, no per-call decision."""
        comm = self._comm
        comm._check_alive()
        entry = comm._coll.get(self._opname)
        if entry is None:
            raise CommError(
                f"{comm.name}: no coll component provides {self._opname}"
            )
        component, fn = entry
        prog = component.persistent_program(
            comm, self._opname, self.buffer, self._args
        )
        if prog is not None:
            self._dispatch = prog
        elif self._opname == "barrier":  # the one bufferless operation
            self._dispatch = lambda _x, f=fn, c=comm: f(c)
        else:
            self._dispatch = (
                lambda x, f=fn, c=comm, a=self._args: f(c, x, *a)
            )
        # Monitoring/memchecker interposition happens once, at bind
        # time — started iterations are pure dispatch (the documented
        # pcollreq trade; DESIGN.md §11).
        from ..core import memchecker

        if memchecker.enabled() and self.buffer is not None:
            memchecker.check_defined(self.buffer,
                                     f"{self._opname} buffer")
        from ..monitoring import MONITOR

        if MONITOR.enabled and self.buffer is not None:
            import jax

            nbytes = sum(
                leaf.nbytes for leaf in jax.tree.leaves(self.buffer)
                if hasattr(leaf, "nbytes")
            )
            MONITOR.record_coll(comm.cid, self._opname, nbytes)

    def _start(self) -> None:
        if self._dispatch is None:
            self._resolve()
        from ..core.counters import SPC
        from ..trace import span as tspan

        SPC.record(self._spc_name)
        # pure-dispatch iterations stay off the span path (the pcollreq
        # latency promise); one instant record marks each start so the
        # timeline still shows persistent traffic.
        tspan.instant("coll.persistent_start", cat="coll",
                      op=self._opname, cid=self._comm.cid)
        self._pending = self._dispatch(self.buffer)

    def _poll(self) -> bool:
        if self.done:
            return True
        if self._pending is not None:
            import jax

            leaves = [
                l for l in jax.tree.leaves(self._pending)
                if hasattr(l, "is_ready")
            ]
            if all(l.is_ready() for l in leaves):
                self._complete(self._pending)
        return self.done

    def wait(self, timeout: float | None = None) -> Status:
        import jax

        from ..core import progress as _progress
        from ..core.errors import RequestError
        from ..core.request import RequestState

        if self.state == RequestState.INACTIVE:
            raise RequestError("wait on persistent collective before start()")
        if not self.done and self._pending is not None:
            if timeout is None:
                jax.block_until_ready(self._pending)
                self._complete(self._pending)
            elif not _progress.ENGINE.progress_until(self._poll, timeout):
                raise TimeoutError("persistent collective wait timed out")
        return self.status


def register_components() -> None:
    """Import all in-tree coll components so they self-register."""
    from . import (  # noqa: F401
        basic,
        demo,
        hier,
        pallas_ring,
        quant,
        selfcoll,
        smcoll,
        sync,
        tuned,
        xla,
    )


_registered = False


def ensure_components() -> None:
    global _registered
    if not _registered:
        register_components()
        _registered = True

"""coll/partitioned — bucketed collectives fed by partition readiness.

The coll-layer consumer of the part framework's core idea: a large
reduction buffer is split into B buckets along the element axis, and
each bucket's allreduce is dispatched the moment the producing
computation marks it ready — instead of one monolithic collective after
ALL the compute finishes. Every bucket goes through the communicator's
normal vtable (`comm.allreduce`), so the existing decision layers —
hier's same-host split, tuned's algorithm table — schedule each bucket
exactly as they would a standalone call of that size; this module adds
only the readiness-driven sequencing (reference analog: the pcollreq
extension's partitioned collectives layered on libnbc schedules).

Bucket ranges come from :func:`ompi_tpu.part.framework.block_range`, the
same block distribution the part/persist component uses for its
partition→transfer mapping, so a bucketed allreduce over E elements and
a partitioned send over E elements agree on what "bucket k" means.
"""

from __future__ import annotations

import itertools
import math
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable

import jax.numpy as jnp

from ..core import progress as _progress
from ..core.counters import SPC
from ..core.errors import ArgumentError, RequestError
from ..part.framework import block_range

SPC.counter(
    "part_coll_buckets_ready",
    "buckets handed to the coll layer by readiness order",
)
SPC.counter(
    "part_tiles_ready_total",
    "gradient tiles marked ready on partitioned allreduces",
)


@contextmanager
def _batch_window():
    """The fastpath dispatch-coalescing window when the shm fabric is
    live (communicator.start_all idiom); transparent otherwise."""
    from ..part.persist import _fabric_engine

    eng = _fabric_engine()
    if eng is None:
        yield
    else:
        with eng.batch_dispatch():
            yield


class BucketedAllreduce:
    """Allreduce over a rank-major ``(size, E)`` buffer, dispatched one
    bucket at a time as ``ready(b)`` is called (any order). ``wait()``
    blocks for the in-flight bucket programs and returns the assembled
    ``(size, E)`` result.

    JAX async dispatch is what makes this overlap real on device: each
    ``ready(b)`` enqueues that bucket's compiled collective and returns
    immediately, so bucket b's wire time runs under the caller's
    compute for bucket b+1.

    Each bucket goes through ``comm.allreduce`` — the normal vtable —
    so coll/tuned decides per bucket at bucket size, including the
    quantized wire tier (coll/quant) when enabled: there is no second
    quantization implementation here, and tuned's refusal rules
    (op/dtype/min-bytes/user-rules veto) apply unchanged.
    """

    def __init__(self, comm, x, op: Any = "sum", nbuckets: int = 8) -> None:
        arr = jnp.asarray(x)
        if arr.ndim < 2 or arr.shape[0] != comm.size:
            raise ArgumentError(
                f"bucketed allreduce needs rank-major (size, E, ...) "
                f"buffer, got shape {arr.shape}"
            )
        elems = arr.shape[1]
        if nbuckets < 1:
            raise ArgumentError(f"nbuckets must be >= 1, got {nbuckets}")
        if nbuckets > elems:
            nbuckets = elems
        self._comm = comm
        self._op = op
        self.buffer = arr
        self.nbuckets = nbuckets
        self._elems = elems
        self._pending: list[Any] = [None] * nbuckets
        self._done = False

    def bucket_range(self, b: int) -> tuple[int, int]:
        """Element range [lo, hi) of bucket b along axis 1."""
        if not 0 <= b < self.nbuckets:
            raise ArgumentError(
                f"bucket {b} out of range [0, {self.nbuckets})"
            )
        return block_range(b, self.nbuckets, self._elems)

    def ready(self, b: int, data=None) -> None:
        """Mark bucket b produced and dispatch its allreduce. ``data``
        optionally supplies fresh values for the bucket's ``(size,
        hi-lo, ...)`` slab (the produce-then-flag pattern); omitted, the
        constructor buffer's slab is used."""
        lo, hi = self.bucket_range(b)
        if self._pending[b] is not None:
            raise RequestError(f"bucket {b} already dispatched")
        slab = self.buffer[:, lo:hi] if data is None else jnp.asarray(data)
        if slab.shape[:2] != (self._comm.size, hi - lo):
            raise ArgumentError(
                f"bucket {b} slab must be ({self._comm.size}, {hi - lo}, "
                f"...), got {slab.shape}"
            )
        SPC.record("part_coll_buckets_ready")
        self._pending[b] = self._comm.allreduce(slab, self._op)

    def ready_all(self) -> None:
        """Dispatch every not-yet-ready bucket in index order."""
        for b in range(self.nbuckets):
            if self._pending[b] is None:
                self.ready(b)

    def wait(self):
        """Block until every bucket's program is complete; return the
        reassembled rank-major ``(size, E, ...)`` result."""
        missing = [b for b, p in enumerate(self._pending) if p is None]
        if missing:
            raise RequestError(
                f"wait() before ready() on buckets {missing}"
            )
        import jax

        out = jnp.concatenate(self._pending, axis=1)
        jax.block_until_ready(out)
        self._done = True
        return out


class PartitionedAllreduce:
    """Persistent tile-granular allreduce of one rank-major ``(size,
    E)`` bucket over the part framework: one ``Psend_init`` /
    ``Precv_init`` pair per peer bound ONCE at construction and re-armed
    every step by ``start()`` (MPI_Start semantics), so the steady-state
    step pays zero setup. ``ready(t, data)`` / ``ready_range(lo, hi,
    data)`` stage a tile's values into the persistent wire buffers and
    fire ``Pready`` on every peer inside one fastpath batch-dispatch
    window; arrivals drain via ``Parrived`` from the progress engine
    (``_pump`` is a registered progress callback), and the root
    accumulates each tile the moment it lands from all peers — so the
    reduction overlaps whatever compute is still producing later tiles.

    Reduction plan: gather-to-root with eager per-tile combine, then one
    ``comm.bcast`` of the reduced buffer fired from the drain callback
    the moment the last tile lands (still overlapped when compute is
    ongoing). Ordered combination is replaced by arrival-order
    combination, hence the commutative-op requirement.

    Wire tier: the bucket's precision is chosen by the SAME tuned
    precedence as a monolithic allreduce of its size
    (``tuned.decide_allreduce``: forced > rules > guards > cache >
    priors). When the decision lands on a quantized algorithm and
    coll/quant supports the op/dtype, tiles travel block-scaled int8 +
    f32 scales (``coll_quant_block``); otherwise exact. Tiles are padded
    to a uniform size (and, on the quant wire, to a scale-block
    multiple) so tile t always owns wire range ``[t*W, (t+1)*W)`` — the
    uniform mapping both sides derive independently.

    Every instance is its own partitioned request pair, so a tile (and
    the partition→transfer re-blocking under it) can never straddle two
    gradient buckets — the bucketer's fusion boundary is the request
    boundary.
    """

    #: Tag allocator for auto-tagged instances: below the user band most
    #: tests use, one user tag per instance (all peers share it — the
    #: derived-namespace matching is per (source, tag)).
    _tags = itertools.count(768)

    def __init__(self, comm, like, op: Any = "sum", tiles: int = 8,
                 tag: int | None = None, root: int = 0,
                 allow_quant: bool | None = None,
                 label: str | None = None,
                 tile_elems: int | None = None,
                 defer_bcast: bool = False,
                 auto_pump: bool = True) -> None:
        import jax
        import numpy as np

        from ..ops import lookup as op_lookup
        from . import quant as _quant
        from . import tuned as _tuned

        arr = jnp.asarray(like)
        if arr.ndim != 2 or arr.shape[0] != comm.size:
            raise ArgumentError(
                f"partitioned allreduce needs a rank-major (size, E) "
                f"template, got shape {arr.shape}"
            )
        self._comm = comm
        self._root = comm.check_rank(root)
        self._op = op_lookup(op)
        if not self._op.commutative:
            raise ArgumentError(
                f"partitioned allreduce combines tiles in arrival "
                f"order; op {self._op.name!r} is not commutative"
            )
        self._elems = int(arr.shape[1])
        if self._elems < 1:
            raise ArgumentError("empty partitioned allreduce template")
        self.tiles = max(1, min(int(tiles), self._elems))
        self._dtype = np.dtype(str(arr.dtype))
        self.label = label or f"cid{comm.cid}"
        # Step-program executor hooks: a compiled step defers the
        # per-bucket bcast (the executor fires ONE merged broadcast for
        # the whole step) and owns a single merged drain callback
        # instead of one engine registration per bucket.
        self._defer_bcast = bool(defer_bcast)
        self._auto_pump = bool(auto_pump)
        self._local = None

        # Per-bucket wire tier under the normal tuned precedence.
        nbytes = self._elems * self._dtype.itemsize
        self.algo = _tuned.decide_allreduce(
            self._op, nbytes, comm.size, arr.dtype,
            allow_quant=allow_quant,
        )
        self.quant_wire = bool(
            _tuned.is_quant_algo(self.algo)
            and _quant.supports(self._op, arr.dtype)
        )

        # Uniform tile geometry over a padded element space. On the
        # quant wire a tile rounds up to a scale-block multiple, which
        # can leave trailing tiles empty — clamp the count so every
        # tile owns at least one logical element. A caller (the sharded
        # ZeRO flow) may pin tile_elems so shard-local tiles stay
        # aligned with the enclosing bucket's tile boundaries.
        if tile_elems is not None:
            et = max(1, min(int(tile_elems), self._elems))
        else:
            et = math.ceil(self._elems / self.tiles)
        if self.quant_wire:
            block = _quant._block_var.value
            et = block * math.ceil(et / block)
            self._scales_per_tile = et // block
            self._wire_tile = et + 4 * self._scales_per_tile  # bytes
            wire_dtype = np.dtype(np.uint8)
        else:
            self._scales_per_tile = 0
            self._wire_tile = et  # elements
            wire_dtype = self._dtype
        self.tiles = math.ceil(self._elems / et)
        self.tile_elems = et
        wire_len = self.tiles * self._wire_tile

        # Persistent pairs, bound once: every peer sends its shard to
        # root; root receives one partitioned request per peer.
        self.tag = next(self._tags) if tag is None else int(tag)
        self._peers = [r for r in range(comm.size) if r != self._root]
        self._send_bufs = {
            r: np.zeros(wire_len, wire_dtype) for r in self._peers
        }
        self._sreqs = {
            r: comm.psend_init(self._send_bufs[r], self.tiles,
                               self._root, self.tag, source=r)
            for r in self._peers
        }
        wire_like = jax.ShapeDtypeStruct((wire_len,), wire_dtype)
        self._rreqs = {
            r: comm.precv_init(self.tiles, r, self.tag,
                               dest=self._root, like=wire_like)
            for r in self._peers
        }
        self._active = False
        # Serializes tile accumulation: the producer thread combines its
        # own contribution inside ready_range() while drain sweeps
        # (progress callbacks, possibly on several threads — test()/
        # test_all() pump the engine without the pumper lock) combine
        # peer arrivals. RLock so a nested pump under _finish_reduce's
        # bcast can never self-deadlock.
        self._lock = threading.RLock()
        self._acc = None
        self._reduce_done = False
        self._result = None
        self.trace_id = 0
        self.t_first_ready = None
        self.t_reduce_done = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "PartitionedAllreduce":
        """Re-arm every persistent pair (one batch-dispatch window) and
        reset per-step tile state."""
        import numpy as np

        from ..communicator import start_all
        from ..trace import span as tspan

        if self._active:
            raise RequestError("start() on an active partitioned "
                               "allreduce")
        start_all(list(self._sreqs.values()) + list(self._rreqs.values()))
        self._active = True
        self._acc = np.zeros(self.tiles * self.tile_elems, np.float64)
        # epoch resets are lock-free on purpose: start() happens-before
        # every pready/_combine of this epoch (MPI partitioned
        # semantics — no partition may be marked ready before start
        # returns), so no combiner thread can race these writes
        self._have = [0] * self.tiles  # commlint: allow(unguardedwrite)
        self._ready = [False] * self.tiles
        self._integrated = {r: [False] * self.tiles for r in self._peers}
        self._tiles_reduced = 0  # commlint: allow(unguardedwrite)
        self._reduce_done = False
        self._result = None
        self._local = None
        self.trace_id = tspan.coll_trace_id(self._comm.cid)
        self.t_first_ready = None
        self.t_reduce_done = None
        if self._auto_pump:
            _progress.register(self._pump)
        return self

    def tile_range(self, t: int) -> tuple[int, int]:
        """Logical element range [lo, hi) of tile t (unpadded space)."""
        if not 0 <= t < self.tiles:
            raise ArgumentError(f"tile {t} out of range [0, {self.tiles})")
        lo = t * self.tile_elems
        return lo, min(lo + self.tile_elems, self._elems)

    # -- producer side ----------------------------------------------------

    def ready(self, t: int, data) -> None:
        """Mark tile t produced: ``data`` is the rank-major ``(size,
        hi-lo)`` slab of fresh values for the tile's element range."""
        self.ready_range(t, t, data)

    def ready_range(self, lo: int, hi: int, data) -> None:
        """Pready_range analog (inclusive bounds): stage tiles lo..hi
        and flag them on every peer in ONE batch-dispatch window."""
        import numpy as np

        from ..trace import span as tspan

        if not self._active:
            raise RequestError("ready() before start()")
        llo, _ = self.tile_range(lo)
        _, lhi = self.tile_range(hi)
        if hi < lo:
            raise ArgumentError(f"ready_range: hi {hi} < lo {lo}")
        host = np.asarray(data)
        if host.shape != (self._comm.size, lhi - llo):
            raise ArgumentError(
                f"tiles [{lo}, {hi}] slab must be "
                f"({self._comm.size}, {lhi - llo}), got {host.shape}"
            )
        for t in range(lo, hi + 1):
            if self._ready[t]:
                raise RequestError(
                    f"tile {t} already marked ready this step"
                )
        now = time.perf_counter()
        if self.t_first_ready is None:
            self.t_first_ready = now
        with _batch_window():
            for r in self._peers:
                if self.quant_wire:
                    wire = np.concatenate([
                        self._encode_tile(host[r], t, llo)
                        for t in range(lo, hi + 1)
                    ])
                elif lhi - llo == (hi - lo + 1) * self.tile_elems:
                    # exact wire, no padding in range: stage the row
                    # itself — no intermediate copy
                    wire = host[r]
                else:
                    # exact wire: only the buffer's LAST tile is ever
                    # short, so one zero-padded copy covers the range
                    wire = np.zeros(
                        (hi - lo + 1) * self.tile_elems, self._dtype)
                    wire[: lhi - llo] = host[r]
                sreq = self._sreqs[r]
                sreq.stage(lo * self._wire_tile,
                           (hi + 1) * self._wire_tile, wire)
                sreq.pready_range(lo, hi)
            for t in range(lo, hi + 1):
                self._ready[t] = True
                tlo, thi = self.tile_range(t)
                self._combine(t, host[self._root, tlo - llo:thi - llo])
                tspan.instant(
                    "part.ready", cat="part", trace_id=self.trace_id,
                    tile=t, bucket=self.label, tag=self.tag,
                )
        SPC.record("part_tiles_ready_total", hi - lo + 1)

    def _encode_tile(self, row, t: int, base_lo: int):
        """Wire image of one peer's tile t from ``row`` (the peer's
        values for the staged logical range starting at base_lo)."""
        import numpy as np

        tlo, thi = self.tile_range(t)
        seg = np.zeros(self.tile_elems, self._dtype)
        seg[: thi - tlo] = row[tlo - base_lo: thi - base_lo]
        if not self.quant_wire:
            return seg
        from . import quant as _quant

        q, scales = _quant.quantize_block_scaled(jnp.asarray(seg))
        return np.concatenate([
            np.asarray(q, np.int8).view(np.uint8),
            np.asarray(scales, np.float32).view(np.uint8),
        ])

    def _decode_tile(self, wire):
        import numpy as np

        if not self.quant_wire:
            return np.asarray(wire, self._dtype)
        from . import quant as _quant

        raw = np.asarray(wire, np.uint8)
        q = raw[: self.tile_elems].view(np.int8)
        scales = raw[self.tile_elems:].view(np.float32)
        return np.asarray(_quant.dequantize_block_scaled(
            jnp.asarray(q), jnp.asarray(scales)))

    # -- consumer side (progress-engine drain) ----------------------------

    def _combine(self, t: int, vals) -> None:
        import numpy as np

        lo = t * self.tile_elems
        v = np.asarray(vals, np.float64).reshape(-1)
        # The producer thread (ready_range's root contribution) and the
        # drain side race here; the _have check-then-act and the
        # _tiles_reduced tally must be atomic or a contribution — or
        # the final count that fires _finish_reduce — is silently lost.
        with self._lock:
            # Unpadded-length ops only: the accumulator's pad region
            # (the final tile's tail) stays zero from start() and is
            # trimmed before use, so it never needs combining.
            view = self._acc[lo: lo + v.size]
            if self._have[t] == 0:
                view[:] = v
            else:
                view[:] = self._op.np_reduce(view, v)
            self._have[t] += 1
            tile_done = self._have[t] == self._comm.size
            if tile_done:
                self._tiles_reduced += 1
            all_done = tile_done and self._tiles_reduced == self.tiles
        if tile_done:
            from ..trace import span as tspan

            tspan.instant(
                "part.arrived", cat="part", trace_id=self.trace_id,
                tile=t, bucket=self.label, tag=self.tag,
            )
        if all_done:
            # Exactly one thread observes the final increment. The
            # bcast runs OUTSIDE the lock so progress pumped under it
            # never contends with a concurrent combiner.
            self._finish_reduce()

    def _pump(self) -> int:
        """Progress callback: one drain sweep per peer, then integrate
        newly arrived tiles (eager reduction under remaining compute)."""
        if not self._active or self._reduce_done:
            return 0
        n = 0
        for r in self._peers:
            rreq = self._rreqs[r]
            # The part component's own progress callback runs the
            # probe-then-recv sweep; this callback only integrates.
            arrived = rreq.arrived_partitions()
            mine = self._integrated[r]
            for t in range(self.tiles):
                if arrived[t] and not mine[t]:
                    # Claim under the lock: direct ENGINE.progress()
                    # callers bypass the pumper lock, so two sweeps can
                    # run concurrently — a tile must integrate once.
                    with self._lock:
                        if mine[t]:
                            continue
                        mine[t] = True
                    vals = self._decode_tile(rreq.partition_view(t))
                    n += 1
                    self._combine(t, vals)
                    if self._reduce_done:
                        return n
        return n

    def _finish_reduce(self) -> None:
        """All tiles combined: cut the padding, broadcast the reduced
        buffer back through the coll vtable (fired from the drain, so it
        still overlaps any remaining producer compute)."""
        import numpy as np

        self.t_reduce_done = time.perf_counter()
        reduced = self._acc[: self._elems].astype(self._dtype)
        if self._defer_bcast:
            # Step-program mode: hold the root-local reduced buffer and
            # let the owning executor broadcast every bucket of the step
            # in ONE merged collective once all nodes finish.
            self._local = reduced
            self._reduce_done = True
            return
        stacked = np.zeros((self._comm.size, self._elems), self._dtype)
        stacked[self._root] = reduced
        self._result = self._comm.bcast(jnp.asarray(stacked), self._root)
        # Flag AFTER the result lands: a concurrent waiter released by
        # this flag must never observe a half-built result.
        self._reduce_done = True

    def local_reduced(self):
        """Root-local reduced 1-D buffer (defer_bcast mode): the step
        executor's input to the merged broadcast."""
        if not self._reduce_done:
            raise RequestError("local_reduced() before reduction done")
        return self._local

    @property
    def tail_armed(self) -> bool:
        """The deferred broadcast tail is armed: reduction complete and
        the root-local buffer held for the merged broadcast. This is
        the slipstream readiness hook — a step program's tail becomes a
        schedulable node exactly when every bucket reports tail_armed,
        at which point the executor may defer the broadcast past
        finish() into the next step's dispatch window. Stays True until
        the next start() re-arms the flow (the buffer survives wait()),
        False always in eager-broadcast mode."""
        return bool(self._defer_bcast and self._reduce_done
                    and self._local is not None)

    @property
    def reduced(self) -> bool:
        """True once every tile has been combined and the reduced
        buffer broadcast — the consumer-side hook: per-bucket apply
        compute may start here while later buckets still reduce."""
        return bool(self._reduce_done)

    def poll(self) -> bool:
        """Drive one progress round and report :attr:`reduced`.

        Routed through the engine's multi-waiter wait loop so a
        consumer thread polling buckets never pumps the drain sweep
        concurrently with a producer-side ``wait()`` — one pumper, the
        rest sleep on completion notifications."""
        if not self._reduce_done:
            _progress.ENGINE.progress_until(
                lambda: self._reduce_done, timeout=0.0)
        return bool(self._reduce_done)

    def wait(self, timeout: float = 60.0):
        """Drive progress until every tile is reduced and every
        persistent sub-request has completed (so start() can re-arm);
        returns the replicated rank-major ``(size, E)`` result."""
        if not self._active:
            raise RequestError("wait() before start()")
        missing = [t for t in range(self.tiles) if not self._ready[t]]
        if missing:
            raise RequestError(
                f"wait() before ready() on tiles {missing}"
            )
        deadline = time.monotonic() + timeout
        try:
            if not _progress.ENGINE.progress_until(
                    lambda: self._reduce_done, timeout=timeout):
                raise RequestError(
                    f"partitioned allreduce {self.label}: tiles "
                    f"{self._tiles_reduced}/{self.tiles} reduced before "
                    f"timeout"
                )
            pend = list(self._sreqs.values()) + list(self._rreqs.values())
            if not _progress.ENGINE.progress_until(
                    lambda: all(r._poll() or r.done for r in pend),
                    timeout=max(0.0, deadline - time.monotonic())):
                raise RequestError(
                    f"partitioned allreduce {self.label}: sub-requests "
                    "incomplete at timeout"
                )
        finally:
            # Success and timeout alike: the drain callback must never
            # outlive the step (a leaked _pump registration pins the
            # instance in the engine forever) and _active must drop so
            # start() can re-arm once the fabric drains.
            _progress.unregister(self._pump)
            self._active = False
        return self._result

    def abort(self) -> None:
        """Tear down an armed step without waiting for completion:
        unregister the drain callback and deactivate so the instance is
        reusable. Any in-flight wire traffic is abandoned to the fabric
        and the step's partial reduction state discarded — re-arming via
        start() is only safe once the persistent sub-requests have
        drained to completion (DESIGN.md §20, abandoned-tile hazards).
        No-op when no step is open."""
        if not self._active:
            return
        _progress.unregister(self._pump)
        self._active = False


def bucketed_allreduce(
    comm,
    x,
    op: Any = "sum",
    nbuckets: int = 8,
    produce: Callable[[int, Any], Any] | None = None,
):
    """Convenience wrapper: allreduce ``x`` bucket-by-bucket. With
    ``produce``, each bucket's slab is ``produce(b, slab)`` — the
    compute whose cost the per-bucket dispatch overlaps; without it this
    is a correctness-equivalent (if pointless) re-bucketing of
    ``comm.allreduce``."""
    br = BucketedAllreduce(comm, x, op, nbuckets)
    for b in range(br.nbuckets):
        if produce is None:
            br.ready(b)
        else:
            lo, hi = br.bucket_range(b)
            br.ready(b, produce(b, br.buffer[:, lo:hi]))
    return br.wait()

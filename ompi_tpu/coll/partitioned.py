"""coll/partitioned — bucketed collectives fed by partition readiness.

The coll-layer consumer of the part framework's core idea: a large
reduction buffer is split into B buckets along the element axis, and
each bucket's allreduce is dispatched the moment the producing
computation marks it ready — instead of one monolithic collective after
ALL the compute finishes. Every bucket goes through the communicator's
normal vtable (`comm.allreduce`), so the existing decision layers —
hier's same-host split, tuned's algorithm table — schedule each bucket
exactly as they would a standalone call of that size; this module adds
only the readiness-driven sequencing (reference analog: the pcollreq
extension's partitioned collectives layered on libnbc schedules).

Bucket ranges come from :func:`ompi_tpu.part.framework.block_range`, the
same block distribution the part/persist component uses for its
partition→transfer mapping, so a bucketed allreduce over E elements and
a partitioned send over E elements agree on what "bucket k" means.
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp

from ..core.counters import SPC
from ..core.errors import ArgumentError, RequestError
from ..part.framework import block_range

SPC.counter(
    "part_coll_buckets_ready",
    "buckets handed to the coll layer by readiness order",
)


class BucketedAllreduce:
    """Allreduce over a rank-major ``(size, E)`` buffer, dispatched one
    bucket at a time as ``ready(b)`` is called (any order). ``wait()``
    blocks for the in-flight bucket programs and returns the assembled
    ``(size, E)`` result.

    JAX async dispatch is what makes this overlap real on device: each
    ``ready(b)`` enqueues that bucket's compiled collective and returns
    immediately, so bucket b's wire time runs under the caller's
    compute for bucket b+1.

    Each bucket goes through ``comm.allreduce`` — the normal vtable —
    so coll/tuned decides per bucket at bucket size, including the
    quantized wire tier (coll/quant) when enabled: there is no second
    quantization implementation here, and tuned's refusal rules
    (op/dtype/min-bytes/user-rules veto) apply unchanged.
    """

    def __init__(self, comm, x, op: Any = "sum", nbuckets: int = 8) -> None:
        arr = jnp.asarray(x)
        if arr.ndim < 2 or arr.shape[0] != comm.size:
            raise ArgumentError(
                f"bucketed allreduce needs rank-major (size, E, ...) "
                f"buffer, got shape {arr.shape}"
            )
        elems = arr.shape[1]
        if nbuckets < 1:
            raise ArgumentError(f"nbuckets must be >= 1, got {nbuckets}")
        if nbuckets > elems:
            nbuckets = elems
        self._comm = comm
        self._op = op
        self.buffer = arr
        self.nbuckets = nbuckets
        self._elems = elems
        self._pending: list[Any] = [None] * nbuckets
        self._done = False

    def bucket_range(self, b: int) -> tuple[int, int]:
        """Element range [lo, hi) of bucket b along axis 1."""
        if not 0 <= b < self.nbuckets:
            raise ArgumentError(
                f"bucket {b} out of range [0, {self.nbuckets})"
            )
        return block_range(b, self.nbuckets, self._elems)

    def ready(self, b: int, data=None) -> None:
        """Mark bucket b produced and dispatch its allreduce. ``data``
        optionally supplies fresh values for the bucket's ``(size,
        hi-lo, ...)`` slab (the produce-then-flag pattern); omitted, the
        constructor buffer's slab is used."""
        lo, hi = self.bucket_range(b)
        if self._pending[b] is not None:
            raise RequestError(f"bucket {b} already dispatched")
        slab = self.buffer[:, lo:hi] if data is None else jnp.asarray(data)
        if slab.shape[:2] != (self._comm.size, hi - lo):
            raise ArgumentError(
                f"bucket {b} slab must be ({self._comm.size}, {hi - lo}, "
                f"...), got {slab.shape}"
            )
        SPC.record("part_coll_buckets_ready")
        self._pending[b] = self._comm.allreduce(slab, self._op)

    def ready_all(self) -> None:
        """Dispatch every not-yet-ready bucket in index order."""
        for b in range(self.nbuckets):
            if self._pending[b] is None:
                self.ready(b)

    def wait(self):
        """Block until every bucket's program is complete; return the
        reassembled rank-major ``(size, E, ...)`` result."""
        missing = [b for b, p in enumerate(self._pending) if p is None]
        if missing:
            raise RequestError(
                f"wait() before ready() on buckets {missing}"
            )
        import jax

        out = jnp.concatenate(self._pending, axis=1)
        jax.block_until_ready(out)
        self._done = True
        return out


def bucketed_allreduce(
    comm,
    x,
    op: Any = "sum",
    nbuckets: int = 8,
    produce: Callable[[int, Any], Any] | None = None,
):
    """Convenience wrapper: allreduce ``x`` bucket-by-bucket. With
    ``produce``, each bucket's slab is ``produce(b, slab)`` — the
    compute whose cost the per-bucket dispatch overlaps; without it this
    is a correctness-equivalent (if pointless) re-bucketing of
    ``comm.allreduce``."""
    br = BucketedAllreduce(comm, x, op, nbuckets)
    for b in range(br.nbuckets):
        if produce is None:
            br.ready(b)
        else:
            lo, hi = br.bucket_range(b)
            br.ready(b, produce(b, br.buffer[:, lo:hi]))
    return br.wait()

"""coll/quant — block-scaled quantized allreduce wire tier.

Large allreduces are wire-bound: on an ICI ring the bytes each link
carries per step bound the achievable GB/s, so halving (bf16) or
quartering (int8) the bytes on the wire raises *effective* bandwidth by
the same factor at negligible accuracy cost for gradient-style sums
(EQuARX, arxiv 2506.17615; the reference MPI stack has no analog — its
wire format is always the user datatype).

Wire formats
  * ``int8``  — block-scaled: the flattened payload is cut into blocks
    of ``coll_quant_block`` elements (default 128, one VREG lane row);
    each block ships as int8 values plus ONE f32 scale
    ``max|x|_block / 127``.  Wire bytes per f32 element:
    ``1 + 4/block`` → 3.88x compression at block=128.
  * ``bf16``  — plain downcast, no scales, 2x compression.

Ring schedule (XLA fallback, runs on the CPU test mesh): the standard
bandwidth-optimal ring (coll/spmd.allreduce_ring) with the carried
partial kept in wire format between hops — each step dequantizes the
arriving block, accumulates the local contribution in f32, and requants
for the next hop ("dequant-accumulate-requant").  The allgather phase
circulates the final quantized block; every rank dequantizes once at
the end.  The fused Pallas variant runs the same schedule with the
int8 payload and the f32 scales as two parallel remote DMAs per step
(the bidirectional-ring two-DMA idiom, pallas_ring.py) and the
dequant/accumulate/requant on the VPU between hops.

Exactness rules: only unordered accumulations with bounded per-step
error go over the quantized wire — in practice SUM on floating-point
payloads.  Order statistics (MAX/MIN), non-commutative ops, joint ops
(MAXLOC) and integer dtypes are *refused* (``supports`` returns False)
and take the exact tier unchanged, so ``allreduce(max)`` through a
quant-enabled communicator stays bit-exact.  The tuned decision layer
(coll/tuned.decide_allreduce) enforces this plus the byte cutoff and
the user-rules veto; see DESIGN.md §12.

Error feedback (opt-in): quantization error is not lost — the residual
``e_t = (x + e_{t-1}) - roundtrip(x + e_{t-1})`` is carried host-side
across calls (EF-SGD lineage), so the *time-averaged* transmitted
signal converges to the exact one at O(1/t).  State lives outside the
compiled plans (they stay pure); see :class:`ErrorFeedback`.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..core import config
from ..core.counters import SPC
from ..ops import lookup as op_lookup
from ..ops.op import Op, _is_joint

__all__ = [
    "supports", "quantize_block_scaled", "dequantize_block_scaled",
    "allreduce_quant_ring", "allreduce_block_quant", "wire_bytes",
    "compression_ratio", "analytic_error_bound", "ErrorFeedback",
    "allreduce_error_feedback",
]

_V = functools.partial(config.register, "coll", "quant")
_enable_var = _V(
    "enable", type=bool, default=False,
    description="Let coll/tuned pick the quantized wire for large "
                "floating-point SUM allreduces",
)
_wire_var = _V(
    "wire", type=str, default="int8",
    description="Quantized wire format: int8 (block-scaled) or bf16 "
                "(downcast)",
)
_block_var = _V(
    "block", type=int, default=128,
    description="Elements per int8 scale block (one f32 scale each)",
)
_min_bytes_var = _V(
    "min_bytes", type=int, default=64 << 10,
    description="Per-rank payload bytes below which quant is refused "
                "(quant trades FLOPs for wire bytes; small messages "
                "are dispatch-bound, not wire-bound)",
)
_ef_var = _V(
    "error_feedback", type=bool, default=False,
    description="Carry the quantization residual across calls "
                "(opt-in; host-side state, see quant.ErrorFeedback)",
)

SPC.counter(
    "coll_quant_bytes_on_wire",
    "bytes actually shipped per hop by quantized allreduces "
    "(logical bytes land on coll_bytes via the normal path)",
    unit="bytes",
)
SPC.counter(
    "coll_quant_bytes_logical",
    "logical (unquantized) bytes the same payloads would have shipped",
    unit="bytes",
)
SPC.counter(
    "coll_quant_compression_ratio",
    "logical/wire byte ratio of the most recent quantized dispatch",
    unit="ratio",
)

_INT8_LEVELS = 127.0


def supports(op: Op | str | None, dtype: Any | None) -> bool:
    """True when (op, dtype) may take the quantized wire: a commutative
    non-joint accumulation with an XLA sum lowering over a floating
    payload.  MAX/MIN are order statistics — any representable-value
    change alters the result, so they are refused and stay exact."""
    if op is None or dtype is None:
        return False
    op = op_lookup(op)
    if not op.commutative or _is_joint(op):
        return False
    if op.xla_reduce != "psum":
        return False
    try:
        return bool(jnp.issubdtype(jnp.dtype(dtype), jnp.floating))
    except TypeError:
        return False


def wire_bytes(logical_bytes: int, itemsize: int = 4,
               wire: str | None = None, block: int | None = None) -> int:
    """Bytes on the wire for a logical payload of ``logical_bytes``."""
    wire = wire or _wire_var.value
    block = block or _block_var.value
    elems = max(1, logical_bytes // max(1, itemsize))
    if wire == "bf16":
        return elems * 2
    nblocks = -(-elems // block)
    return elems + 4 * nblocks


def compression_ratio(itemsize: int = 4, wire: str | None = None,
                      block: int | None = None) -> float:
    """Logical/wire ratio for the configured format (analytic)."""
    logical = 1 << 20
    return logical * itemsize / wire_bytes(logical * itemsize, itemsize,
                                           wire, block)


def record_wire_stats(logical_bytes: int, itemsize: int,
                      wire: str | None = None,
                      block: int | None = None) -> None:
    """SPC pvars for one quantized dispatch (host-side, at plan time)."""
    wb = wire_bytes(logical_bytes, itemsize, wire, block)
    SPC.record("coll_quant_bytes_on_wire", wb)
    SPC.record("coll_quant_bytes_logical", logical_bytes)
    SPC.counter("coll_quant_compression_ratio").set(
        logical_bytes / max(1, wb))
    from ..trace import span as tspan

    tspan.instant("quant.wire", cat="coll", logical=logical_bytes,
                  wire=wb, ratio=round(logical_bytes / max(1, wb), 3))


# ---------------------------------------------------------------------------
# Block-scaled codec (traced; used by the XLA ring, the tests and the
# error-feedback residual — the pallas kernel re-implements the same
# math on (rows, 128) tiles).
# ---------------------------------------------------------------------------

def quantize_block_scaled(x: jax.Array, block: int | None = None
                          ) -> tuple[jax.Array, jax.Array]:
    """Flat f32/bf16 ``(m,)`` payload (m % block == 0) -> (int8 ``(m,)``
    values, f32 ``(m/block,)`` scales).  scale = max|x|_block / 127;
    all-zero blocks get scale 1 so the roundtrip stays exact."""
    block = block or _block_var.value
    v = x.astype(jnp.float32).reshape(-1, block)
    m = jnp.max(jnp.abs(v), axis=1, keepdims=True)
    scale = jnp.where(m > 0, m / _INT8_LEVELS, 1.0)
    q = jnp.clip(jnp.round(v / scale), -_INT8_LEVELS, _INT8_LEVELS)
    return q.astype(jnp.int8).reshape(-1), scale.reshape(-1)


def dequantize_block_scaled(q: jax.Array, scales: jax.Array,
                            block: int | None = None) -> jax.Array:
    """Inverse of :func:`quantize_block_scaled` (f32 result)."""
    block = block or _block_var.value
    v = q.astype(jnp.float32).reshape(-1, block)
    return (v * scales.reshape(-1, 1)).reshape(-1)


def quant_roundtrip(x: jax.Array, wire: str | None = None,
                    block: int | None = None) -> jax.Array:
    """What the far side reconstructs from x's wire image (any shape)."""
    wire = wire or _wire_var.value
    if wire == "bf16":
        return x.astype(jnp.bfloat16).astype(jnp.float32).reshape(x.shape)
    block = block or _block_var.value
    flat = x.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    q, s = quantize_block_scaled(flat, block)
    out = dequantize_block_scaled(q, s, block)
    if pad:
        out = out[:-pad]
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# XLA ppermute ring (the fallback that runs on the CPU test mesh).
# Same schedule as spmd.allreduce_ring; the carried partial travels in
# wire format between hops.
# ---------------------------------------------------------------------------

def _flatten_pad_quant(x: jax.Array, n: int, block: int
                       ) -> tuple[jax.Array, int]:
    """Ravel and zero-pad so each of the n ring blocks is a whole
    number of scale blocks (element count divides n*block)."""
    flat = x.reshape(-1)
    total = flat.shape[0]
    quantum = n * block
    padded = -(-total // quantum) * quantum
    if padded != total:
        flat = jnp.pad(flat, (0, padded - total))
    return flat, total


def allreduce_quant_ring(x: jax.Array, axis_name: str, op: Any = "sum",
                         wire: str | None = None,
                         block: int | None = None) -> jax.Array:
    """Inside shard_map: quantized-wire ring allreduce of the local
    contribution ``x``.  Callers (coll/tuned, parallel/bucketer) gate
    on :func:`supports`; calling this with an unsupported op raises."""
    op = op_lookup(op)
    wire = wire or _wire_var.value
    block = block or _block_var.value
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    if op.xla_reduce != "psum":
        raise ValueError(
            f"quant wire supports SUM only, got {op.name!r} "
            f"(tuned must refuse this op)"
        )
    rank = lax.axis_index(axis_name)
    flat, total = _flatten_pad_quant(x, n, block)
    blocks = flat.astype(jnp.float32).reshape(n, -1)
    m = blocks.shape[1]
    right = [(i, (i + 1) % n) for i in range(n)]

    if wire == "bf16":
        # Reduce-scatter: carry travels as bf16, accumulate in f32.
        carry = jnp.take(blocks, rank, axis=0).astype(jnp.bfloat16)
        for k in range(n - 1):
            recvd = lax.ppermute(carry, axis_name, right)
            idx = (rank - k - 1) % n
            acc = recvd.astype(jnp.float32) + jnp.take(blocks, idx, axis=0)
            carry = acc.astype(jnp.bfloat16)
        # Allgather: circulate the finished bf16 block.
        out = jnp.zeros((n, m), jnp.bfloat16)
        out = out.at[(rank + 1) % n].set(carry)
        cur = carry
        for k in range(n - 1):
            cur = lax.ppermute(cur, axis_name, right)
            out = out.at[(rank - k) % n].set(cur)
        deq = out.astype(jnp.float32)
    else:
        q, s = quantize_block_scaled(jnp.take(blocks, rank, axis=0), block)
        for k in range(n - 1):
            q = lax.ppermute(q, axis_name, right)
            s = lax.ppermute(s, axis_name, right)
            idx = (rank - k - 1) % n
            acc = dequantize_block_scaled(q, s, block) \
                + jnp.take(blocks, idx, axis=0)
            q, s = quantize_block_scaled(acc, block)
        out_q = jnp.zeros((n, m), jnp.int8)
        out_s = jnp.zeros((n, m // block), jnp.float32)
        out_q = out_q.at[(rank + 1) % n].set(q)
        out_s = out_s.at[(rank + 1) % n].set(s)
        for k in range(n - 1):
            q = lax.ppermute(q, axis_name, right)
            s = lax.ppermute(s, axis_name, right)
            out_q = out_q.at[(rank - k) % n].set(q)
            out_s = out_s.at[(rank - k) % n].set(s)
        deq = jax.vmap(
            lambda qq, ss: dequantize_block_scaled(qq, ss, block)
        )(out_q, out_s)

    return deq.reshape(-1)[:total].reshape(x.shape).astype(x.dtype)


def analytic_error_bound(per_rank: Any, axis_elems: int | None = None,
                         wire: str | None = None,
                         block: int | None = None) -> jax.Array:
    """Worst-case per-element |error| of the quantized-wire ring
    allreduce, from the GLOBAL ``(n, ...)`` stack of per-rank inputs.

    An element passes through at most n quantization events (the seed
    quantize + n-2 reduce-scatter requants + the final requant whose
    image the allgather circulates), each contributing at most half an
    int8 step of the then-current block scale.  Partial sums (and the
    errors already absorbed into them) are bounded by
    S_b = sum_r max|x_r|_block, so

        |err| <= 2 * n * S_b / 254          (int8; factor 2 absorbs the
                                             error-growth compounding)
        |err| <= 2 * n * S_b * 2**-9        (bf16 half-ulp)

    Returns the bound with the input's trailing shape.
    """
    wire = wire or _wire_var.value
    block = block or _block_var.value
    stack = jnp.asarray(per_rank, jnp.float32)
    n = stack.shape[0]
    flat = stack.reshape(n, -1)
    pad = (-flat.shape[1]) % (n * block)
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    nb = flat.shape[1] // block
    per_block_max = jnp.max(
        jnp.abs(flat).reshape(n, nb, block), axis=2
    )
    s = jnp.sum(per_block_max, axis=0)                       # (nb,)
    step = (1.0 / (2 * _INT8_LEVELS)) if wire != "bf16" else 2.0 ** -9
    bound = jnp.repeat(2.0 * n * s * step, block)
    if pad:
        bound = bound[:-pad]
    return bound.reshape(stack.shape[1:])


# ---------------------------------------------------------------------------
# Fused Pallas kernel: the same dequant-accumulate-requant ring with the
# int8 payload and the f32 scales as two parallel remote DMAs per step
# (the two-DMA-per-step idiom of pallas_ring._allreduce_bidir_kernel)
# under the same two-slot + capacity-semaphore credit flow control.
# Payload layout per ring block: (rows, 128) int8, rows % 128 == 0, one
# f32 scale per row kept as (rows/128, 128).  CPU testing requires
# Mosaic TPU-interpret mode (pallas_ring._interpret()).
# ---------------------------------------------------------------------------

def _quant_rows(x):
    """(rows, 128) f32 -> ((rows, 128) int8, (rows/128, 128) f32)."""
    m = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(m > 0, m / _INT8_LEVELS, 1.0)
    q = jnp.clip(jnp.round(x / scale), -_INT8_LEVELS, _INT8_LEVELS)
    return q.astype(jnp.int8), scale.reshape(-1, 128)


def _dequant_rows(q, s):
    return q.astype(jnp.float32) * s.reshape(-1, 1)


def _quant_allreduce_kernel(axis_name, n, x_ref, out_ref,
                            buf_q, buf_s,
                            ssem_q, rsem_q, csem_q,
                            ssem_s, rsem_s, csem_s):
    """Ring allreduce over the quantized wire: 2(n-1) steps, each
    moving one int8 block + its scale row-group to the right neighbor
    as two DMAs issued back-to-back (both in flight before either is
    awaited), with dequant-accumulate-requant between hops."""
    from jax.experimental.pallas import tpu as pltpu

    me = lax.axis_index(axis_name)
    right = lax.rem(me + 1, n)
    left = lax.rem(me - 1 + n, n)

    first = lax.rem(me - 1 + n, n)
    q0, s0 = _quant_rows(x_ref[first])
    buf_q[0] = q0
    buf_s[0] = s0
    # Post-seed credit for each buffer's slot 0 (pallas_ring credit
    # flow: gates the upstream step-1 write; no implicit entry barrier).
    for csem in (csem_q, csem_s):
        pltpu.semaphore_signal(csem.at[0], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    for step in range(2 * (n - 1)):
        slot = step % 2
        nslot = (step + 1) % 2
        if step >= 1:
            pltpu.semaphore_wait(csem_q.at[nslot], 1)
            pltpu.semaphore_wait(csem_s.at[nslot], 1)
        dma_q = pltpu.make_async_remote_copy(
            src_ref=buf_q.at[slot], dst_ref=buf_q.at[nslot],
            send_sem=ssem_q.at[slot], recv_sem=rsem_q.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        dma_s = pltpu.make_async_remote_copy(
            src_ref=buf_s.at[slot], dst_ref=buf_s.at[nslot],
            send_sem=ssem_s.at[slot], recv_sem=rsem_s.at[nslot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        dma_q.start()
        dma_s.start()
        dma_q.wait()
        dma_s.wait()
        if step < n - 1:
            blk = lax.rem(me - step - 2 + 2 * n, n)
            acc = _dequant_rows(buf_q[nslot], buf_s[nslot]) + x_ref[blk]
            qn, sn = _quant_rows(acc)
            comm_done = step == n - 2
            buf_q[nslot] = qn
            buf_s[nslot] = sn
            if comm_done:
                # First finished block: dequantized locally; its WIRE
                # image is what the allgather phase circulates, so all
                # ranks reconstruct identical values.
                out_ref[blk] = _dequant_rows(qn, sn)
        else:
            blk = lax.rem(me - (step - (n - 1)) - 1 + 2 * n, n)
            out_ref[blk] = _dequant_rows(buf_q[nslot], buf_s[nslot])
        if step < 2 * (n - 1) - 2:
            for csem in (csem_q, csem_s):
                pltpu.semaphore_signal(
                    csem.at[nslot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )


def allreduce_block_quant(b: jax.Array, axis_name: str, op: Any = "sum"
                          ) -> jax.Array:
    """shard_map body: local contribution -> fully reduced buffer over
    the fused Pallas quantized ring (int8 wire, per-128-lane scales)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from . import pallas_ring as pr

    op = op_lookup(op)
    if op.xla_reduce != "psum":
        raise ValueError(f"quant pallas ring supports SUM only, "
                         f"got {op.name!r}")
    n = lax.axis_size(axis_name)
    if n == 1:
        return b
    shape = b.shape
    flat = b.astype(jnp.float32).reshape(-1)
    # Each ring block: (rows, 128) with rows % 128 == 0 so the f32
    # scale-per-row group reshapes to whole (rows/128, 128) tiles.
    quantum = n * 128 * 128
    pad = (-flat.size) % quantum
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.size // (n * 128)
    blocks = flat.reshape(n, rows, 128)
    kernel = functools.partial(_quant_allreduce_kernel, axis_name, n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, rows, 128), jnp.float32,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rows, 128), jnp.int8),
            pltpu.VMEM((2, rows // 128, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=12,
        ),
        interpret=pr._interpret(),
    )(blocks)
    flat_out = out.reshape(-1)
    if pad:
        flat_out = flat_out[:-pad]
    return flat_out.reshape(shape).astype(b.dtype)


# ---------------------------------------------------------------------------
# Error feedback (opt-in, host-side state across calls).
# ---------------------------------------------------------------------------

class ErrorFeedback:
    """Carries the quantization residual across repeated allreduces of
    the same logical tensor (gradient steps): each call compensates the
    input with the previous residual, transmits the wire image of the
    compensated value, and keeps the new residual

        e_t = (x_t + e_{t-1}) - roundtrip(x_t + e_{t-1}).

    Telescoping gives sum_t transmitted = sum_t x_t + e_{-1} - e_T with
    ``e_T`` bounded by one quantization step — the time-averaged
    transmitted signal converges to the exact one at O(1/t).  State is
    per-instance and host-side; the compiled collective plans stay
    pure (DESIGN.md §12)."""

    def __init__(self, wire: str | None = None,
                 block: int | None = None) -> None:
        self.wire = wire
        self.block = block
        self.residual = None

    @staticmethod
    def enabled_by_config() -> bool:
        return bool(_ef_var.value)

    def compensate(self, x: jax.Array) -> jax.Array:
        """Return the value to transmit for ``x`` (the wire roundtrip
        of the residual-compensated input) and update the residual."""
        xc = jnp.asarray(x, jnp.float32)
        if self.residual is not None:
            xc = xc + self.residual
        sent = quant_roundtrip(xc, self.wire, self.block)
        self.residual = xc - sent
        return sent.astype(jnp.asarray(x).dtype)

    def residual_norm(self) -> float:
        if self.residual is None:
            return 0.0
        return float(jnp.linalg.norm(self.residual.reshape(-1)))


def allreduce_error_feedback(comm, x, state: ErrorFeedback,
                             op: Any = "sum"):
    """Vtable allreduce of the EF-compensated wire image of ``x`` (a
    rank-major ``(size, ...)`` buffer; the residual is elementwise, so
    one state instance covers all rank rows)."""
    return comm.allreduce(state.compensate(x), op)

"""coll/xla — XLA-native collective component.

The TPU analog of letting the fabric do the work: every operation lowers
to XLA's own collective primitives (psum / all_gather / psum_scatter /
all_to_all), which the TPU runtime maps to its ICI-optimal schedules.
This is the baseline high-performance component; coll/tuned sits above
it with the explicit algorithm space (reference analog: coll/basic vs
coll/tuned layering, but here the *basic* fabric path is already
device-optimal — the inversion SURVEY §2.3 coll/cuda calls out).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import ArgumentError
from ..ops import lookup as op_lookup
from . import spmd
from .framework import COLL, CollComponent, compile_plan, rank_major_check


def _leaf_check(comm, x):
    """Validate every pytree leaf is rank-major; return the pytree."""
    leaves = jax.tree.leaves(x)
    if not leaves:
        raise ArgumentError("empty buffer")
    for leaf in leaves:
        if jnp.ndim(leaf) < 1 or jnp.shape(leaf)[0] != comm.size:
            raise ArgumentError(
                f"expected rank-major leading dim {comm.size}, got shape "
                f"{jnp.shape(leaf)}"
            )
    return x


def _dtype_key(x) -> tuple:
    return tuple(
        (jnp.shape(l), str(jnp.asarray(l).dtype)) for l in jax.tree.leaves(x)
    )


@COLL.register
class XlaColl(CollComponent):
    NAME = "xla"
    PRIORITY = 40
    DESCRIPTION = "XLA-native fabric collectives (psum/all_gather/...)"

    def _allreduce_plan(self, comm, x, op):
        """The compiled program behind allreduce; x is leaf-checked and
        comm.size > 1. Split out so persistent_program can hand the
        bound plan to PersistentColl."""
        key = ("allreduce", "native", op.cache_key, _dtype_key(x))
        return compile_plan(
            comm, key, lambda b: spmd.allreduce_native(b, "ranks", op)
        )

    def allreduce(self, comm, x, op):
        op = op_lookup(op)
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return x
        return self._allreduce_plan(comm, x, op)(x)

    def persistent_program(self, comm, opname, x, args):
        if opname != "allreduce":
            return None
        op = op_lookup(args[0])
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return lambda b: b
        return self._allreduce_plan(comm, x, op)

    def bcast(self, comm, x, root):
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return x
        key = ("bcast", "native", root, _dtype_key(x))
        plan = compile_plan(
            comm, key, lambda b: spmd.bcast_native(b, "ranks", root=root)
        )
        return plan(x)

    def reduce(self, comm, x, op, root):
        op = op_lookup(op)
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return jax.tree.map(lambda l: l[0], x)
        # Same program as allreduce (root slicing happens outside the
        # plan) — share its cache entry instead of recompiling.
        key = ("allreduce", "native", op.cache_key, _dtype_key(x))
        plan = compile_plan(
            comm, key, lambda b: spmd.allreduce_native(b, "ranks", op)
        )
        out = plan(x)
        # Only root's block is the defined result (MPI semantics); slice it.
        return jax.tree.map(lambda l: l[root], out)

    def allgather(self, comm, x):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None]
        key = ("allgather", "native", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.allgather_native(b, "ranks")
        )
        return plan(x)

    def reduce_scatter_block(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            raise ArgumentError(
                f"reduce_scatter_block needs (size, size, ...) buffer, got "
                f"{x.shape}"
            )
        if comm.size == 1:
            return x[:, 0]
        key = ("reduce_scatter_block", "native", op.cache_key, x.shape,
               str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.reduce_scatter_native(b, "ranks", op)
        )
        return plan(x)

    def alltoall(self, comm, x):
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            raise ArgumentError(
                f"alltoall needs (size, size, ...) buffer, got {x.shape}"
            )
        if comm.size == 1:
            return x
        key = ("alltoall", "native", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.alltoall_native(b, "ranks")
        )
        return plan(x)

    def gather(self, comm, x, root):
        out = self.allgather(comm, x)
        return out[root]

    def scatter(self, comm, x, root):
        # Scatter is pure data movement: reshard root's (size, ...) buffer
        # one block per rank. XLA/ICI does the fan-out in the device_put.
        import jax.numpy as jnp

        arr = jnp.asarray(x)
        if arr.shape[0] != comm.size:
            raise ArgumentError(
                f"scatter needs (size, ...) buffer, got {arr.shape}"
            )
        return comm.put_rank_major(arr)

    def scan(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x
        key = ("scan", "native", op.cache_key, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.scan_native(b, "ranks", op)
        )
        return plan(x)

    def exscan(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return jnp.zeros_like(x)
        key = ("exscan", "native", op.cache_key, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.exscan_native(b, "ranks", op)
        )
        return plan(x)

    def barrier(self, comm):
        """Returns the fabric token array; the communicator layer blocks
        on it for barrier() and wraps it for ibarrier()."""
        if comm.size == 1:
            return None
        key = ("barrier",)
        plan = compile_plan(
            comm, key,
            lambda b: spmd.barrier("ranks") + 0 * b,
        )
        token = comm.put_rank_major(jnp.zeros((comm.size,), jnp.int32))
        return plan(token)

    # -- vector (ragged) variants ------------------------------------------
    # Device path: pad every ragged block to the max count (one device
    # pad each, no host round-trip), run the cached fixed-shape fabric
    # plan, slice the live rows back out on device. Counts are static
    # Python ints, so each distinct count profile compiles once — the
    # reference's alltoallv walks its displs arrays per call; here the
    # profile IS the executable (SURVEY §7: persistent pre-compiled
    # plans).

    @staticmethod
    def _pad_stack(comm, values, max_len):
        n = comm.size
        blocks = []
        for v in values:
            arr = jnp.asarray(v)
            pad = [(0, max_len - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
            blocks.append(jnp.pad(arr, pad))
        return comm.from_rank_values(blocks)

    def allgatherv(self, comm, values):
        if len(values) != comm.size:
            raise ArgumentError(
                f"need one block per rank ({comm.size}), got {len(values)}"
            )
        counts = [jnp.shape(v)[0] for v in values]
        m = max(counts) if counts else 0
        if m == 0:
            first = jnp.asarray(values[0])
            return jax.device_put(first, comm.replicated_sharding())
        gathered = self.allgather(comm, self._pad_stack(comm, values, m))
        # gathered: (size, size, m, ...) rank-major; every rank's copy is
        # identical, take rank 0's and drop the padding per segment.
        full = gathered[0]
        return jnp.concatenate(
            [full[r, :c] for r, c in enumerate(counts)], axis=0
        )

    def alltoallv(self, comm, blocks):
        n = comm.size
        if len(blocks) != n:
            raise ArgumentError(f"need {n} send lists, got {len(blocks)}")
        counts = [[jnp.shape(blocks[s][d])[0] for d in range(n)]
                  for s in range(n)]
        m = max((c for row in counts for c in row), default=0)
        if m == 0:
            return [jnp.asarray(blocks[0][d]) for d in range(n)]
        padded = []
        for s in range(n):
            row = []
            for d in range(n):
                arr = jnp.asarray(blocks[s][d])
                pad = [(0, m - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                row.append(jnp.pad(arr, pad))
            padded.append(jnp.stack(row))
        x = comm.from_rank_values(padded)  # (size, size, m, ...)
        swapped = self.alltoall(comm, x)  # (dst, src, m, ...)
        return [
            jnp.concatenate(
                [swapped[d, s, :counts[s][d]] for s in range(n)], axis=0
            )
            for d in range(n)
        ]

    def reduce_scatter(self, comm, values, counts, op):
        op = op_lookup(op)
        n = comm.size
        if len(values) != n:
            raise ArgumentError(
                f"need one buffer per rank ({n}), got {len(values)}"
            )
        if len(counts) != n:
            raise ArgumentError(f"need {n} counts, got {len(counts)}")
        total = sum(counts)
        for v in values:
            if jnp.shape(v)[0] != total:
                raise ArgumentError(
                    f"buffer rows {jnp.shape(v)[0]} != sum(counts) {total}"
                )
        x = comm.from_rank_values(values)
        red = self.allreduce(comm, x, op)[0]
        out, start = [], 0
        for r, c in enumerate(counts):
            out.append(
                jax.device_put(red[start:start + c], comm.devices[r])
            )
            start += c
        return out

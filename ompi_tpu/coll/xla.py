"""coll/xla — XLA-native collective component.

The TPU analog of letting the fabric do the work: every operation lowers
to XLA's own collective primitives (psum / all_gather / psum_scatter /
all_to_all), which the TPU runtime maps to its ICI-optimal schedules.
This is the baseline high-performance component; coll/tuned sits above
it with the explicit algorithm space (reference analog: coll/basic vs
coll/tuned layering, but here the *basic* fabric path is already
device-optimal — the inversion SURVEY §2.3 coll/cuda calls out).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.errors import ArgumentError
from ..ops import lookup as op_lookup
from . import spmd
from .framework import COLL, CollComponent, compile_plan, rank_major_check


def _leaf_check(comm, x):
    """Validate every pytree leaf is rank-major; return the pytree."""
    leaves = jax.tree.leaves(x)
    if not leaves:
        raise ArgumentError("empty buffer")
    for leaf in leaves:
        if jnp.ndim(leaf) < 1 or jnp.shape(leaf)[0] != comm.size:
            raise ArgumentError(
                f"expected rank-major leading dim {comm.size}, got shape "
                f"{jnp.shape(leaf)}"
            )
    return x


def _dtype_key(x) -> tuple:
    return tuple(
        (jnp.shape(l), str(jnp.asarray(l).dtype)) for l in jax.tree.leaves(x)
    )


@COLL.register
class XlaColl(CollComponent):
    NAME = "xla"
    PRIORITY = 40
    DESCRIPTION = "XLA-native fabric collectives (psum/all_gather/...)"

    def allreduce(self, comm, x, op):
        op = op_lookup(op)
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return x
        key = ("allreduce", "native", op.cache_key, _dtype_key(x))
        plan = compile_plan(
            comm, key, lambda b: spmd.allreduce_native(b, "ranks", op)
        )
        return plan(x)

    def bcast(self, comm, x, root):
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return x
        key = ("bcast", "native", root, _dtype_key(x))
        plan = compile_plan(
            comm, key, lambda b: spmd.bcast_native(b, "ranks", root=root)
        )
        return plan(x)

    def reduce(self, comm, x, op, root):
        op = op_lookup(op)
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return jax.tree.map(lambda l: l[0], x)
        # Same program as allreduce (root slicing happens outside the
        # plan) — share its cache entry instead of recompiling.
        key = ("allreduce", "native", op.cache_key, _dtype_key(x))
        plan = compile_plan(
            comm, key, lambda b: spmd.allreduce_native(b, "ranks", op)
        )
        out = plan(x)
        # Only root's block is the defined result (MPI semantics); slice it.
        return jax.tree.map(lambda l: l[root], out)

    def allgather(self, comm, x):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None]
        key = ("allgather", "native", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.allgather_native(b, "ranks")
        )
        return plan(x)

    def reduce_scatter_block(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            raise ArgumentError(
                f"reduce_scatter_block needs (size, size, ...) buffer, got "
                f"{x.shape}"
            )
        if comm.size == 1:
            return x[:, 0]
        key = ("reduce_scatter_block", "native", op.cache_key, x.shape,
               str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.reduce_scatter_native(b, "ranks", op)
        )
        return plan(x)

    def alltoall(self, comm, x):
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            raise ArgumentError(
                f"alltoall needs (size, size, ...) buffer, got {x.shape}"
            )
        if comm.size == 1:
            return x
        key = ("alltoall", "native", x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.alltoall_native(b, "ranks")
        )
        return plan(x)

    def gather(self, comm, x, root):
        out = self.allgather(comm, x)
        return out[root]

    def scatter(self, comm, x, root):
        # Scatter is pure data movement: reshard root's (size, ...) buffer
        # one block per rank. XLA/ICI does the fan-out in the device_put.
        import jax.numpy as jnp

        arr = jnp.asarray(x)
        if arr.shape[0] != comm.size:
            raise ArgumentError(
                f"scatter needs (size, ...) buffer, got {arr.shape}"
            )
        return comm.put_rank_major(arr)

    def scan(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x
        key = ("scan", "native", op.cache_key, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.scan_native(b, "ranks", op)
        )
        return plan(x)

    def exscan(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return jnp.zeros_like(x)
        key = ("exscan", "native", op.cache_key, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: spmd.exscan_native(b, "ranks", op)
        )
        return plan(x)

    def barrier(self, comm):
        """Returns the fabric token array; the communicator layer blocks
        on it for barrier() and wraps it for ibarrier()."""
        if comm.size == 1:
            return None
        key = ("barrier",)
        plan = compile_plan(
            comm, key,
            lambda b: spmd.barrier("ranks") + 0 * b,
        )
        token = comm.put_rank_major(jnp.zeros((comm.size,), jnp.int32))
        return plan(token)

"""Collective operations framework (reference: ompi/mca/coll)."""

from . import spmd

__all__ = ["spmd"]
